"""Chaos harness for the training resilience layer (PR 6's loadtest-SLO
idea applied to training): drive a DETERMINISTIC, seeded fault schedule
through the real ``train_maml_system.py`` CLI and assert that the job
finishes with zero human intervention, that every fault maps to its
documented recovery, and that recovery is a measured number
(``train_recovery_s``, MTTR per fault class), not a hope.

Fault classes (all injected via ``MAML_FAULTS`` — ``utils/faultinject.py``):

=============  ===============================  ============================
class          injection                        documented recovery
=============  ===============================  ============================
``sigterm``    SIGTERM after a dispatch         emergency checkpoint, exit
                                                75, resume SAME mesh,
                                                bit-exact replay
``kill``       SIGKILL (mesh-worker death)      no handler runs; resume
                                                replays from the last
                                                published checkpoint,
                                                bit-exact (seed
                                                fast-forward)
``hang``       wedged dispatch thread           watchdog: stack dump +
                                                exit 76, resume on the
                                                next-smaller viable mesh,
                                                bit-exact (mesh-portable
                                                checkpoints)
``enospc``     ENOSPC on checkpoint writes      in-process write retry
                                                (PR 3), params unaffected
``nan``        NaN batch                        on-device skip
                                                (``--on_nonfinite skip``),
                                                finite and progressing
``producer``   transient loader error in the    stager retry-then-skip
               stager                           under the quarantine
                                                budget, ``data_fault``
                                                telemetry
=============  ===============================  ============================

Bit-exactness vs an unfaulted twin run (``--baseline``) is asserted exactly
where the contract promises it — schedules of preemption/crash/ENOSPC
faults whose recovery REPLAYS the same trajectory. Schedules containing
skip-path faults (``nan``, ``producer``) assert finite-and-progressing
instead (the skipped update/batch changes the trajectory by design), and so
does a ``hang`` that actually degraded the mesh (a smaller dp extent
changes the cross-task reduction order; the restore itself is pinned
bit-exact by ``tests/test_mesh_checkpoint.py``). A ``hang`` with no
smaller viable mesh replays exactly and keeps the bit-exact contract.

Quickstart (synthesizes a tiny dataset + config; ~2 min on CPU):

    python tools/chaos_train.py --tiny --seed 7 \
        --schedule enospc,sigterm,kill,hang --devices 2 --baseline --json

``--schedule auto`` seeds-shuffles all six classes. Verdict JSON on stdout;
exit 0 iff the run completed, every fault recovered as documented, and the
bit-exact/finite contract held. ``measure_recovery`` is the bench hook
behind the ``train_recovery_s`` key (bench.py standard emission).
"""

from __future__ import annotations

import argparse
import json
import os
import random
import shutil
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # `python tools/chaos_train.py` from anywhere
    sys.path.insert(0, REPO)

ENTRY = "train_maml_system.py"

#: Exit codes the supervisor maps to recoveries (kept in sync with
#: experiment_builder.REQUEUE_EXIT_CODE / utils.watchdog.HANG_EXIT_CODE).
REQUEUE_EXIT_CODE = 75
HANG_EXIT_CODE = 76

FAULT_CLASSES = ("sigterm", "kill", "hang", "enospc", "nan", "producer")

#: Faults that terminate the training process (each ends a phase); the
#: others recover in-process and ride along in a phase's fault plan.
STOPPING = {"sigterm", "kill", "hang"}

#: Skip-path faults: recovery changes the trajectory by design, so the
#: bit-exact-vs-baseline contract does not apply to schedules using them.
SKIP_PATH = {"nan", "producer"}

#: Per-phase subprocess timeout — generous over compile + the watchdog
#: deadline; a phase that outlives it is itself an undetected hang.
PHASE_TIMEOUT_S = 420


def make_tiny_dataset(root: str, seed: int = 0) -> None:
    """Synthesizes the tests' tiny omniglot-layout PNG dataset (4 alphabets
    x 5 characters x 4 images) under ``root``."""
    from PIL import Image

    rng = np.random.RandomState(seed)
    for a in range(4):
        for c in range(5):
            d = os.path.join(root, f"Alphabet{a}", f"character{c:02d}")
            os.makedirs(d, exist_ok=True)
            proto = rng.randint(0, 2, (28, 28)) * 255
            for i in range(4):
                img = proto.copy()
                flip = rng.rand(28, 28) < 0.05
                img[flip] = 255 - img[flip]
                Image.fromarray(img.astype(np.uint8), mode="L").convert(
                    "1"
                ).save(os.path.join(d, f"{i}.png"))


def tiny_config(workdir: str, name: str, devices: int = 1) -> str:
    """Writes the tiny chaos config JSON (2-stage 4-filter MAML++, 3 epochs
    x 2 iters, resilience knobs tuned for fast deterministic recovery) and
    returns its path."""
    cfg = {
        "experiment_name": os.path.join(workdir, name),
        "dataset_name": "omniglot_mini",
        "dataset_path": "omniglot_mini",
        "image_height": 28, "image_width": 28, "image_channels": 1,
        "reset_stored_filepaths": False, "reverse_channels": False,
        "labels_as_int": False, "sets_are_pre_split": False,
        "load_into_memory": False,
        "train_val_test_split": [0.5, 0.25, 0.25],
        "indexes_of_folders_indicating_class": [-3, -2],
        "num_dataprovider_workers": 2,
        "seed": 104, "train_seed": 1, "val_seed": 0,
        "num_of_gpus": 1, "batch_size": 2, "samples_per_iter": 1,
        "num_classes_per_set": 5, "num_samples_per_class": 1,
        "num_target_samples": 1,
        "total_epochs": 3, "total_iter_per_epoch": 2,
        "total_epochs_before_pause": 100,
        "num_evaluation_tasks": 4, "evaluate_on_test_set_only": False,
        "max_models_to_save": 5,
        "model": "maml++",
        "num_stages": 2, "cnn_num_filters": 4, "conv_padding": True,
        "max_pooling": True, "norm_layer": "batch_norm",
        "per_step_bn_statistics": True,
        "number_of_training_steps_per_iter": 2,
        "number_of_evaluation_steps_per_iter": 2,
        "second_order": False, "first_order_to_second_order_epoch": -1,
        "use_multi_step_loss_optimization": True,
        "multi_step_loss_num_epochs": 2,
        "learnable_per_layer_per_step_inner_loop_learning_rate": True,
        "enable_inner_loop_optimizable_bn_params": False,
        "learnable_bn_gamma": True, "learnable_bn_beta": True,
        "meta_learning_rate": 0.001, "min_learning_rate": 1e-5,
        "task_learning_rate": 0.1, "init_inner_loop_learning_rate": 0.1,
        # Resilience knobs under test. on_nonfinite=skip so a NaN batch
        # exercises the on-device discard; identical in the baseline so
        # exact schedules still compare bit-for-bit (skip is the identity
        # on finite batches).
        "on_nonfinite": "skip",
        "watchdog": True, "watchdog_min_s": 10.0, "watchdog_factor": 3.0,
        "checkpoint_async": True, "data_fault_budget": 4,
        "data_parallel_devices": devices, "model_parallel_devices": 1,
    }
    path = os.path.join(workdir, f"{name}.json")
    with open(path, "w") as f:
        json.dump(cfg, f)
    return path


def _child_env(workdir: str, devices: int, faults: dict | None) -> dict:
    env = dict(os.environ)
    env["DATASET_DIR"] = workdir
    env["JAX_PLATFORMS"] = "cpu"
    # REPLACE any inherited forced-device-count flag (e.g. the test
    # suite's 8-device conftest) with this run's topology.
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={max(devices, 1)}"
    )
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if faults:
        env["MAML_FAULTS"] = ",".join(
            f"{key}={value}" for key, value in faults.items()
        )
    else:
        env.pop("MAML_FAULTS", None)
    return env


def _latest_iter(exp_dir: str) -> int:
    path = os.path.join(exp_dir, "saved_models", "train_model_latest")
    try:
        with np.load(path) as archive:
            state = json.loads(bytes(archive["__experiment_state__"]).decode())
        return int(state["current_iter"])
    except Exception:  # noqa: BLE001 — no checkpoint yet
        return 0


def _final_leaves(exp_dir: str) -> dict:
    path = os.path.join(exp_dir, "saved_models", "train_model_latest")
    with np.load(path) as archive:
        return {
            k: archive[k] for k in archive.files if k.startswith("leaf_")
        }


def _read_events(exp_dir: str) -> list[dict]:
    path = os.path.join(exp_dir, "logs", "telemetry.jsonl")
    events = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    events.append(json.loads(line))
    except OSError:
        pass
    return events


#: In-process fault classes whose recovery EVIDENCE lives in buffered
#: telemetry / end-of-epoch state: they must not ride a phase ended by an
#: evidence-destroying stopper (SIGKILL / the watchdog's ``os._exit``
#: flush nothing), or the verdict cannot witness a recovery that did in
#: fact happen. SIGTERM phases drain the writer and flush telemetry on
#: the way out, so they can carry riders.
_EVIDENCE_RIDERS = {"nan", "enospc"}
_EVIDENCE_DESTROYING = {"kill", "hang"}


def _partition_phases(schedule: list[str]) -> list[list[str]]:
    """Splits the schedule into per-process phases: in-process faults ride
    along until a stopping fault ends the phase; evidence-needing riders
    are deferred past kill/hang phases to the next surviving phase;
    leftovers join the final clean-to-completion phase."""
    phases: list[list[str]] = []
    pending: list[str] = []
    for fault in schedule:
        if fault in STOPPING and fault in _EVIDENCE_DESTROYING:
            riders = [f for f in pending if f in _EVIDENCE_RIDERS]
            phases.append(
                [f for f in pending if f not in _EVIDENCE_RIDERS] + [fault]
            )
            pending = riders
        elif fault in STOPPING:
            phases.append(pending + [fault])
            pending = []
        else:
            pending.append(fault)
    phases.append(pending)  # final phase (possibly fault-free)
    return phases


def _plan_phase(
    faults: list[str],
    resume_iter: int,
    epoch_len: int,
    total_iters: int,
) -> dict:
    """Maps this phase's fault classes onto a concrete ``MAML_FAULTS``
    plan relative to the resume point.

    The stopping fault (at most one) lands on the FIRST EPOCH BOUNDARY
    after at least one completed dispatch: the phase always makes progress
    first (so the watchdog's compile-bearing first dispatch is behind a
    hang), and a same-phase ``nan`` trip has been folded into the
    persisted ``nonfinite_trips_total`` — the skip policy's accounting is
    epoch-boundary-based, so a stopper firing mid-epoch would lose the
    (persisted-evidence of the) trip even though the poisoned update
    itself is discarded on-device either way. ``sigterm_due`` runs after
    the epoch-boundary block by design (experiment_builder), so the
    boundary checkpoint and the stop compose in that order."""
    stop_at = -(-(resume_iter + 1) // epoch_len) * epoch_len
    plan: dict = {}
    for fault in faults:
        if fault == "nan":
            # 0-based index of the consuming iteration (poison_batch):
            # the first dispatch after resume trains on the NaN batch.
            plan["nan_at_iter"] = resume_iter
        elif fault == "producer":
            plan["producer_fail_at_iter"] = resume_iter + 1
        elif fault == "enospc":
            plan["fail_next_writes"] = 2
        elif fault == "sigterm":
            plan["sigterm_at_iter"] = stop_at
        elif fault == "kill":
            plan["sigkill_at_iter"] = stop_at
        elif fault == "hang":
            # Pre-increment index: wedges the dispatch AFTER the boundary
            # at stop_at (capped so the wedged dispatch exists at all).
            plan["hang_at_iter"] = min(stop_at, total_iters - 1)
        else:
            raise ValueError(f"unknown fault class {fault!r}")
    return plan


def run_chaos(
    workdir: str,
    schedule: list[str],
    devices: int = 1,
    baseline: bool = False,
    verbose: bool = True,
) -> dict:
    """Runs the schedule through the real CLI under supervision; returns
    the verdict dict (see module docstring). ``workdir`` must already hold
    the tiny dataset (``make_tiny_dataset``)."""
    for fault in schedule:
        if fault not in FAULT_CLASSES:
            raise ValueError(
                f"unknown fault class {fault!r}; expected {FAULT_CLASSES}"
            )

    def log(msg):
        if verbose:
            print(f"chaos: {msg}", file=sys.stderr, flush=True)

    cfg_path = tiny_config(workdir, "chaos_exp", devices=devices)
    with open(cfg_path) as f:
        cfg = json.load(f)
    exp_dir = cfg["experiment_name"]
    test_csv = os.path.join(exp_dir, "logs", "test_summary.csv")

    phases = _partition_phases(schedule)

    current_devices = devices
    verdict_faults: dict = {}
    recoveries: dict = {}
    fired_stoppers: list[tuple[str, float]] = []
    max_extra_phases = 4
    phase_idx = 0

    epoch_len = int(cfg["total_iter_per_epoch"])
    total_iters = int(cfg["total_epochs"]) * epoch_len
    for phase_faults in phases:
        resume_iter = _latest_iter(exp_dir)
        plan = _plan_phase(phase_faults, resume_iter, epoch_len, total_iters)
        stopper = next((f for f in phase_faults if f in STOPPING), None)
        log(
            f"phase {phase_idx}: faults={phase_faults or ['none']} "
            f"resume_iter={resume_iter} devices={current_devices}"
        )
        proc = subprocess.run(
            [sys.executable, "-u", ENTRY, "--name_of_args_json_file",
             cfg_path],
            cwd=REPO, env=_child_env(workdir, current_devices, plan),
            capture_output=True, text=True, timeout=PHASE_TIMEOUT_S,
            check=False,
        )
        t_exit = time.time()
        rc = proc.returncode
        log(f"phase {phase_idx}: rc={rc}")
        phase_idx += 1
        for fault in phase_faults:
            verdict_faults.setdefault(fault, {})["rc"] = rc
        if stopper is not None:
            fired_stoppers.append((stopper, t_exit))
            expected = {
                "sigterm": rc == REQUEUE_EXIT_CODE,
                "kill": rc < 0 or rc == 137,
                "hang": rc == HANG_EXIT_CODE,
            }[stopper]
            verdict_faults[stopper]["exit_as_documented"] = bool(expected)
            if stopper == "hang" and rc == HANG_EXIT_CODE:
                # Mirror the dispatcher's degraded-mesh policy: resume on
                # the next-smaller viable extent (suspect the topology).
                from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
                    degraded_dp_extent,
                )

                smaller = degraded_dp_extent(
                    current_devices,
                    global_batch=(
                        int(cfg.get("num_of_gpus", 1))
                        * int(cfg["batch_size"])
                        * int(cfg.get("samples_per_iter", 1))
                    ),
                    task_chunk=int(cfg.get("task_chunk", 0) or 0),
                )
                if smaller is not None:
                    log(f"hang: degrading mesh dp{current_devices} -> "
                        f"dp{smaller}")
                    current_devices = smaller
                    cfg["data_parallel_devices"] = smaller
                    with open(cfg_path, "w") as f:
                        json.dump(cfg, f)
        elif rc != 0 and not os.path.exists(test_csv):
            verdict_faults.setdefault("unexpected_exit", {})["rc"] = rc
            break
        if os.path.exists(test_csv):
            break

    # The schedule may leave the run unfinished (e.g. it ended on a
    # stopping fault): keep resuming fault-free until completion.
    while not os.path.exists(test_csv) and max_extra_phases > 0:
        max_extra_phases -= 1
        log(f"clean resume phase (devices={current_devices})")
        proc = subprocess.run(
            [sys.executable, "-u", ENTRY, "--name_of_args_json_file",
             cfg_path],
            cwd=REPO, env=_child_env(workdir, current_devices, None),
            capture_output=True, text=True, timeout=PHASE_TIMEOUT_S,
            check=False,
        )
        if proc.returncode not in (0, REQUEUE_EXIT_CODE):
            log(f"clean resume phase rc={proc.returncode}")
            break
        phase_idx += 1

    completed = os.path.exists(test_csv)
    events = _read_events(exp_dir)

    # Recovery evidence per fault class, from the run's own telemetry —
    # the observability layer is the chaos verdict's witness.
    if "sigterm" in verdict_faults:
        verdict_faults["sigterm"]["recovered"] = (
            verdict_faults["sigterm"].get("exit_as_documented", False)
            and any(e.get("type") == "preemption" for e in events)
        )
    if "kill" in verdict_faults:
        verdict_faults["kill"]["recovered"] = (
            verdict_faults["kill"].get("exit_as_documented", False)
            and completed
        )
    if "hang" in verdict_faults:
        hang_events = [e for e in events if e.get("type") == "hang"]
        verdict_faults["hang"]["recovered"] = (
            verdict_faults["hang"].get("exit_as_documented", False)
            and bool(hang_events)
            and os.path.exists(
                os.path.join(exp_dir, "logs", "hang_stacks.txt")
            )
        )
        verdict_faults["hang"]["degraded_to_devices"] = current_devices
    if "enospc" in verdict_faults:
        verdict_faults["enospc"]["recovered"] = any(
            e.get("type") == "checkpoint_save" and e.get("attempts", 1) > 1
            for e in events
        )
    if "producer" in verdict_faults:
        verdict_faults["producer"]["recovered"] = any(
            e.get("type") == "data_fault" and not e.get("fatal", True)
            for e in events
        )
    if "nan" in verdict_faults:
        state = {}
        try:
            with np.load(
                os.path.join(exp_dir, "saved_models", "train_model_latest")
            ) as archive:
                state = json.loads(
                    bytes(archive["__experiment_state__"]).decode()
                )
        except Exception:  # noqa: BLE001 — verdict stays False
            pass
        verdict_faults["nan"]["recovered"] = (
            float(state.get("nonfinite_trips_total", 0.0)) > 0.0
        )

    # MTTR per stopping fault: fault-process exit -> the resumed process's
    # checkpoint_load event (unix timestamps from the telemetry stream).
    for stopper, t_exit in fired_stoppers:
        loads = [
            e["t"] for e in events
            if e.get("type") == "checkpoint_load" and e["t"] >= t_exit
        ]
        if loads:
            recoveries[stopper] = round(min(loads) - t_exit, 3)
            verdict_faults[stopper]["recovery_s"] = recoveries[stopper]

    bitexact = None
    final_finite = None
    try:
        leaves = _final_leaves(exp_dir)
        final_finite = all(
            np.isfinite(np.asarray(a, np.float64)).all()
            for a in leaves.values()
        )
    except Exception:  # noqa: BLE001 — no final checkpoint
        leaves = None

    exact_contract = (
        not (set(schedule) & SKIP_PATH) and current_devices == devices
    )
    if baseline and exact_contract and leaves is not None:
        base_cfg = tiny_config(workdir, "chaos_baseline", devices=devices)
        log("baseline: unfaulted twin run")
        subprocess.run(
            [sys.executable, "-u", ENTRY, "--name_of_args_json_file",
             base_cfg],
            cwd=REPO, env=_child_env(workdir, devices, None),
            capture_output=True, text=True, timeout=PHASE_TIMEOUT_S,
            check=False,
        )
        base_exp = os.path.join(workdir, "chaos_baseline")
        try:
            base_leaves = _final_leaves(base_exp)
            bitexact = set(base_leaves) == set(leaves) and all(
                np.array_equal(base_leaves[k], leaves[k]) for k in leaves
            )
        except Exception:  # noqa: BLE001 — baseline itself failed
            bitexact = False

    recovered_all = all(
        info.get("recovered", False)
        for fault, info in verdict_faults.items()
        if fault in FAULT_CLASSES
    )
    restart_recoveries = sorted(recoveries.values())
    verdict = {
        "schedule": schedule,
        "devices": devices,
        "phases": phase_idx,
        "completed": completed,
        "faults": verdict_faults,
        "mttr_s": recoveries,
        "train_recovery_s": (
            restart_recoveries[len(restart_recoveries) // 2]
            if restart_recoveries else None
        ),
        "bitexact_vs_baseline": bitexact,
        "mesh_degraded": current_devices != devices,
        "final_finite": final_finite,
        "ok": bool(
            completed
            and recovered_all
            and (bitexact is not False)
            and (final_finite is not False)
        ),
    }
    return verdict


DISPATCH = "train_maml_system_dispatch.py"

#: Wall budget for the whole kill-a-host run (fleet phase + watchdog +
#: coordinated shutdown + degraded resume to completion).
KILLHOST_TIMEOUT_S = 600


def _killhost_env(workdir: str) -> dict:
    """Fleet env: each worker process owns ONE virtual CPU device (the
    dispatcher's per-rank distributed flags make 2x1 = a 2-device global
    mesh, dp across "hosts")."""
    env = dict(os.environ)
    env["DATASET_DIR"] = workdir
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # The kill plan rides --fault_rank targeting in the dispatcher: only
    # the victim rank's child env keeps MAML_FAULTS.
    env["MAML_FAULTS"] = "sigkill_at_iter=3"
    return env


def run_killhost_chaos(workdir: str, verbose: bool = True) -> dict:
    """Kill-a-host chaos: a 2-process CPU fleet driven through the REAL
    dispatcher CLI; rank 1 is SIGKILLed mid-epoch (a lost host). Documented
    recovery: the survivor's watchdog detects the silent collective and
    exits 76, the dispatcher coordinates shutdown, appends a
    host-attributed audit row, auto-resumes DEGRADED on 1 process from the
    last published checkpoint (rank 0 is the single writer; checkpoints
    are mesh-portable), and the run completes with zero intervention.
    ``multihost_recovery_s`` = survivor hang-detection -> resumed
    checkpoint load, from the shared telemetry stream."""

    def log(msg):
        if verbose:
            print(f"chaos: {msg}", file=sys.stderr, flush=True)

    cfg_path = tiny_config(workdir, "chaos_killhost", devices=2)
    with open(cfg_path) as f:
        cfg = json.load(f)
    exp_dir = cfg["experiment_name"]
    test_csv = os.path.join(exp_dir, "logs", "test_summary.csv")

    log("kill-a-host: 2-process fleet via the dispatcher, SIGKILL rank 1 "
        "at iter 3")
    t0 = time.time()
    proc = subprocess.run(
        [sys.executable, "-u", DISPATCH, cfg_path,
         "--num_processes", "2", "--fault_rank", "1",
         "--fleet_grace_s", "25", "--max_hangs", "4"],
        cwd=REPO, env=_killhost_env(workdir),
        capture_output=True, text=True, timeout=KILLHOST_TIMEOUT_S,
        check=False,
    )
    wall_s = time.time() - t0
    log(f"dispatcher rc={proc.returncode} after {wall_s:.1f}s")

    completed = os.path.exists(test_csv)
    events = _read_events(exp_dir)

    # Survivor-side detection evidence. The peer loss surfaces one of two
    # ways depending on the collective transport: a SILENT WEDGE in the
    # next forced read (real TPU pods — the survivor's watchdog fires a
    # rank-attributed ``hang`` event and exits 76) or a FAST collective
    # error (CPU gloo: connection-reset raises at the read). Either way
    # the supervisor observes the fleet die and recovers identically; the
    # hang event is recorded when present, not required.
    hangs = [
        e for e in events
        if e.get("type") == "hang" and int(e.get("process_index", -1)) == 0
    ]
    # Host-attributed supervisor audit rows: the host-loss row is stamped
    # with the OBSERVED death time and attributes rank 1 (exit-order
    # attribution — the killed host, not the crashed/hung survivors).
    audit_rows: list[str] = []
    try:
        with open(os.path.join(exp_dir, "logs", "interruptions.csv")) as f:
            audit_rows = [line.strip() for line in f][1:]
    except OSError:
        pass
    host_loss_rows = [r for r in audit_rows if "host-loss:rank1" in r]
    degrade_rows = [r for r in audit_rows if "procs2->procs1" in r]

    # MTTR: observed host death (the audit row's stamp) -> the degraded
    # resume's checkpoint load, from the shared telemetry stream.
    recovery_s = None
    if host_loss_rows:
        t_loss = min(float(r.split(",")[0]) for r in host_loss_rows)
        loads = [
            float(e["t"]) for e in events
            if e.get("type") == "checkpoint_load" and float(e["t"]) >= t_loss
        ]
        if loads:
            recovery_s = round(min(loads) - t_loss, 3)

    final_finite = None
    try:
        final_finite = all(
            np.isfinite(np.asarray(a, np.float64)).all()
            for a in _final_leaves(exp_dir).values()
        )
    except Exception:  # noqa: BLE001 — no final checkpoint
        pass

    verdict = {
        "schedule": ["killhost"],
        "devices": 2,
        "num_processes": 2,
        "completed": completed,
        "dispatcher_rc": proc.returncode,
        "survivor_hang_detected": bool(hangs),
        "host_loss_audit_rows": host_loss_rows,
        "degraded_to_one_process": bool(degrade_rows),
        "multihost_recovery_s": recovery_s,
        "final_finite": final_finite,
        "wall_s": round(wall_s, 1),
        "ok": bool(
            completed
            and proc.returncode == 0
            and host_loss_rows
            and degrade_rows
            and recovery_s is not None
            and final_finite is not False
        ),
    }
    if not verdict["ok"] and verbose:
        sys.stderr.write(proc.stdout[-3000:] + proc.stderr[-3000:])
    return verdict


#: Wall budget for the promote chaos run (trainer to completion + the
#: daemon resolving every candidate + the forced rollback).
PROMOTE_TIMEOUT_S = 600

PROMOTION_DAEMON = os.path.join("tools", "promotion_daemon.py")


def _daemon_argv(exp_dir: str, url: str) -> list[str]:
    return [
        sys.executable, "-u", os.path.join(REPO, PROMOTION_DAEMON),
        "--watch", os.path.join(exp_dir, "saved_models"),
        "--target", url,
        "--journal", os.path.join(exp_dir, "logs", "promotions.jsonl"),
        "--staging", os.path.join(exp_dir, "promotion_staging"),
        "--telemetry", os.path.join(exp_dir, "logs", "telemetry.jsonl"),
        "--poll_interval_s", "0.3",
        "--slo_watch_s", "2.0", "--slo_poll_s", "0.2",
        "--min_requests", "1",
        "--promote_retries", "4", "--promote_backoff_s", "0.3",
    ]


def _read_journal(exp_dir: str) -> list[dict]:
    from howtotrainyourmamlpytorch_tpu.serve.resilience.promotion import (
        PromotionJournal,
    )

    return PromotionJournal.load(
        os.path.join(exp_dir, "logs", "promotions.jsonl")
    )


def run_promote_chaos(
    workdir: str,
    verbose: bool = True,
    kill_trainer: bool = True,
    epochs: int = 5,
) -> dict:
    """The continuous train→serve loop, end to end, zero intervention:

    a REAL ``train_maml_system.py`` run publishes epoch checkpoints
    (async writer + ``.ready`` markers) while a 2-replica pool serves
    continuous loadtest traffic and the promotion-daemon CLI (its own
    process) watches the checkpoint dir and drives canary-first
    promotions through the pool's HTTP front door. Faults, each mapping
    to its documented recovery:

    * ``kill_trainer_mid_publish`` — the trainer is SIGKILLed inside the
      torn window (epoch archive on disk, marker not): the watcher never
      sees the half-published epoch, the resumed run re-publishes from
      ``latest`` and the loop continues;
    * ``corrupt_candidate_at`` (daemon env) — the daemon's first staged
      candidate is truncated: rejected pre-publish, journaled + typed
      telemetry, trainer files untouched;
    * harness SIGKILL of the daemon after its first ``promoted`` row —
      the restarted daemon replays the journal and resumes idempotently
      (no double-promote, no skipped candidate);
    * ``regress_after_promote`` — armed before the LAST candidate's
      publish: the freshly promoted state serves NaN logits, the
      post-publish SLO watch sees the nonfinite counter move and rolls
      the fleet back to the retained last-known-good digest.

    Asserted outcome: >= 3 clean automatic promotions, the corrupt
    rejection, the rollback, loadtest SLO PASS with ZERO failed requests
    through every swap, and the miner turning the run's own telemetry
    into a non-empty replay manifest."""
    import threading as _threading

    from howtotrainyourmamlpytorch_tpu.serve import make_http_server
    from howtotrainyourmamlpytorch_tpu.serve.pool import (
        PoolConfig,
        ReplicaPool,
    )
    from howtotrainyourmamlpytorch_tpu.serve.resilience.replica import (
        LocalReplica,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry import events as tel_events
    from howtotrainyourmamlpytorch_tpu.telemetry.events import EventLog
    from howtotrainyourmamlpytorch_tpu.utils import faultinject
    from tools.serve_loadtest import run_loadtest, synth_episodes

    def log(msg):
        if verbose:
            print(f"chaos: {msg}", file=sys.stderr, flush=True)

    cfg_path = tiny_config(workdir, "chaos_promote", devices=1)
    with open(cfg_path) as f:
        cfg = json.load(f)
    cfg["total_epochs"] = int(epochs)
    cfg["total_iter_per_epoch"] = 1
    with open(cfg_path, "w") as f:
        json.dump(cfg, f)
    exp_dir = cfg["experiment_name"]
    os.makedirs(os.path.join(exp_dir, "logs"), exist_ok=True)
    test_csv = os.path.join(exp_dir, "logs", "test_summary.csv")
    telemetry_path = os.path.join(exp_dir, "logs", "telemetry.jsonl")

    # -- serving fleet (in-process 2-replica pool + HTTP front door) ----
    previous_dataset_dir = os.environ.get("DATASET_DIR")
    os.environ["DATASET_DIR"] = workdir
    sink = EventLog(telemetry_path)
    previous_sink = tel_events.install(sink)
    from tools.serve_maml import build_learner

    learner = build_learner("maml", cfg_path)
    way = int(cfg["num_classes_per_set"])
    query = int(cfg["num_target_samples"])

    def factory(index: int) -> LocalReplica:
        import jax

        from howtotrainyourmamlpytorch_tpu.serve import (
            ServeConfig,
            ServingAPI,
        )

        api = ServingAPI(
            learner, learner.init_state(jax.random.PRNGKey(0)),
            ServeConfig(meta_batch_size=2, max_wait_ms=0.0),
        )
        api.engine.warmup([(way, 1, query)])
        return LocalReplica(api, replica_id=f"local-{index}")

    pool = ReplicaPool(
        factory,
        PoolConfig(
            n_replicas=2, health_interval_s=0.1, restart_backoff_s=0.2,
            min_uptime_s=0.0,
        ),
    )
    daemon_proc: dict | None = None
    server = None
    stop_traffic = _threading.Event()
    loadtest_results: list[dict] = []
    verdict: dict = {"schedule": ["promote"], "ok": False}
    try:
        if not pool.wait_ready(timeout=300.0):
            raise RuntimeError("2-replica pool never became healthy")
        server = make_http_server(pool, "127.0.0.1", 0)
        port = server.server_address[1]
        url = f"http://127.0.0.1:{port}"
        server_thread = _threading.Thread(
            target=server.serve_forever, daemon=True
        )
        server_thread.start()
        log(f"pool front door on {url}")

        # -- continuous loadtest traffic (in-process, tagged) -----------
        bb = learner.cfg.backbone
        image_shape = (bb.image_channels, bb.image_height, bb.image_width)
        episodes = synth_episodes(
            16, way=way, shot=1, query=query, image_shape=image_shape,
            seed=3,
        )

        def offer_traffic():
            while not stop_traffic.is_set():
                loadtest_results.append(run_loadtest(
                    pool, episodes, rate_qps=4.0, duration_s=5.0,
                    p99_budget_ms=5_000.0, error_slo=0.0, timeout_s=10.0,
                    seed=len(loadtest_results), sample_health=False,
                    tag_seed_base=50_000,
                ))

        traffic_thread = _threading.Thread(target=offer_traffic, daemon=True)
        traffic_thread.start()

        # -- promotion daemon (own process; corrupt-candidate armed) ----
        daemon_env = dict(os.environ)
        daemon_env["PYTHONPATH"] = REPO + os.pathsep + daemon_env.get(
            "PYTHONPATH", ""
        )
        daemon_env["JAX_PLATFORMS"] = "cpu"
        daemon_env["MAML_FAULTS"] = "corrupt_candidate_at=600"
        daemon_proc = daemon_holder = {"proc": subprocess.Popen(
            _daemon_argv(exp_dir, url), cwd=REPO, env=daemon_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )}
        log("promotion daemon started (corrupt_candidate_at=600)")
        t_deadline = time.time() + PROMOTE_TIMEOUT_S

        # -- mid-run daemon SIGKILL + restart (concurrent killer) -------
        def kill_and_restart_daemon():
            while time.time() < t_deadline and not stop_traffic.is_set():
                rows = _read_journal(exp_dir)
                if any(r["phase"] == "promoted" for r in rows):
                    log("SIGKILL the daemon mid-run (first promoted row)")
                    daemon_holder["proc"].kill()
                    daemon_holder["proc"].wait(timeout=30)
                    restart_env = dict(daemon_env)
                    restart_env.pop("MAML_FAULTS", None)
                    daemon_holder["proc"] = subprocess.Popen(
                        _daemon_argv(exp_dir, url), cwd=REPO,
                        env=restart_env,
                        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                    )
                    verdict["daemon_killed_mid_run"] = True
                    return
                time.sleep(0.3)

        killer_thread = _threading.Thread(
            target=kill_and_restart_daemon, daemon=True
        )
        killer_thread.start()

        # -- the real trainer, SIGKILLed mid-publish then resumed -------
        trainer_faults = (
            {"kill_trainer_mid_publish": 1} if kill_trainer else None
        )
        trainer_runs = 0
        while not os.path.exists(test_csv) and trainer_runs < 4:
            trainer_runs += 1
            log(f"trainer run {trainer_runs} "
                f"(faults={trainer_faults or 'none'})")
            proc = subprocess.run(
                [sys.executable, "-u", ENTRY, "--name_of_args_json_file",
                 cfg_path],
                cwd=REPO, env=_child_env(workdir, 1, trainer_faults),
                capture_output=True, text=True, timeout=PHASE_TIMEOUT_S,
                check=False,
            )
            if trainer_faults and proc.returncode in (-9, 137):
                verdict["trainer_killed_mid_publish"] = True
            trainer_faults = None
        verdict["trainer_completed"] = os.path.exists(test_csv)

        # -- wait for every trainer candidate to resolve ----------------
        expected_clean = int(epochs) - (3 if kill_trainer else 2)
        while time.time() < t_deadline:
            rows = _read_journal(exp_dir)
            clean = [r for r in rows if r["phase"] == "slo_ok"]
            rejected = [r for r in rows if r["phase"] == "rejected"]
            if len(clean) >= expected_clean and rejected:
                break
            sink.flush()
            time.sleep(0.5)
        killer_thread.join(timeout=60)

        # -- forced post-promotion regression -> automatic rollback -----
        # Armed BEFORE the regressing candidate exists, so the ordering
        # is deterministic: the harness drops one more valid candidate
        # (fresh init weights + recorded val stats), the daemon promotes
        # it, the publish arms nan_next_logits via promotion_applied,
        # live traffic goes non-finite inside the SLO window, and the
        # daemon rolls the fleet back to the retained last-known-good.
        import jax as _jax

        from howtotrainyourmamlpytorch_tpu.utils.checkpoint import (
            publish_done_marker,
        )

        log("arming regress_after_promote + dropping the bad candidate")
        faultinject.activate(
            faultinject.FaultPlan(regress_after_promote=8)
        )
        bad_path = os.path.join(
            exp_dir, "saved_models", f"train_model_{int(epochs) + 40}"
        )
        learner.save_model(
            bad_path, learner.init_state(_jax.random.PRNGKey(7)),
            {"current_iter": 999, "best_val_acc": 0.9,
             "per_epoch_statistics": {"val_accuracy_mean": [0.9]}},
        )
        publish_done_marker(bad_path)
        rollback_seen = False
        while time.time() < t_deadline:
            rows = _read_journal(exp_dir)
            if any(r["phase"] == "rolled_back" for r in rows):
                rollback_seen = True
                break
            sink.flush()
            time.sleep(0.5)
        sink.flush()
        verdict["rollback_seen"] = rollback_seen
    finally:
        stop_traffic.set()
        try:
            faultinject.deactivate()
        except Exception:  # noqa: BLE001
            pass
        if daemon_proc is not None:
            proc = daemon_proc.get("proc")
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
        try:
            traffic_thread.join(timeout=60)
        except Exception:  # noqa: BLE001
            pass
        if server is not None:
            server.shutdown()
            server.server_close()
            server_thread.join(timeout=10)
        pool.close()
        tel_events.install(previous_sink)
        sink.flush()
        if previous_dataset_dir is None:
            os.environ.pop("DATASET_DIR", None)
        else:
            os.environ["DATASET_DIR"] = previous_dataset_dir

    # -- verdict --------------------------------------------------------
    rows = _read_journal(exp_dir)
    events = _read_events(exp_dir)
    promoted_rows = [r for r in rows if r["phase"] == "promoted"]
    clean_digests = [r["digest"] for r in rows if r["phase"] == "slo_ok"]
    rejected = [r for r in rows if r["phase"] == "rejected"]
    rolled = [r for r in rows if r["phase"] == "rolled_back"]
    # No double-promote across the daemon SIGKILL: at most one promoted
    # row per digest unless explicitly marked resumed.
    digest_counts: dict = {}
    for r in promoted_rows:
        digest_counts[r["digest"]] = digest_counts.get(r["digest"], 0) + 1
    double_promoted = [
        d for d, n in digest_counts.items()
        if n > 1 and not any(
            r.get("resumed") for r in promoted_rows if r["digest"] == d
        )
    ]
    offered = sum(r["offered"] for r in loadtest_results)
    ok_requests = sum(r["completed_ok"] for r in loadtest_results)
    slo_pass = bool(loadtest_results) and all(
        r["slo_pass"] for r in loadtest_results
    )
    corrupt_rejections = [
        r for r in rejected if r["reason"] in ("corrupt", "digest_mismatch")
    ]
    rollback_to_lkg = bool(
        rolled and clean_digests and rolled[-1].get("to") == clean_digests[-1]
    )
    # Feedback edge: the run's own telemetry mines into a replay manifest.
    mined = 0
    try:
        from tools.episode_miner import mine_events, select_hard_episodes

        mined = len(select_hard_episodes(
            mine_events(events), max_margin=1.0, top=64
        ))
    except Exception:  # noqa: BLE001 — verdict field stays 0
        pass
    verdict.update({
        "devices": 1,
        "completed": verdict.get("trainer_completed", False),
        "promotions": len(clean_digests),
        "promoted_digests": sorted(set(r["digest"] for r in promoted_rows)),
        "corrupt_rejected": len(corrupt_rejections),
        "rejected_reasons": sorted(r["reason"] for r in rejected),
        "rollback_to_lkg": rollback_to_lkg,
        "double_promoted": double_promoted,
        "daemon_restarted": True,
        "loadtest_offered": offered,
        "loadtest_ok": ok_requests,
        "loadtest_failed": offered - ok_requests,
        "loadtest_slo_pass": slo_pass,
        "mined_episodes": mined,
        "telemetry_promotion_events": sorted({
            e["type"] for e in events
            if str(e.get("type", "")).startswith("promotion")
            or str(e.get("type", "")).startswith("slo_")
        }),
        "ok": bool(
            verdict.get("trainer_completed")
            and len(clean_digests) >= 3
            and corrupt_rejections
            and verdict.get("rollback_seen")
            and rollback_to_lkg
            and not double_promoted
            and slo_pass
            and offered > 0
            and offered == ok_requests
            and mined > 0
        ),
    })
    if not verdict["ok"] and verbose:
        log(f"verdict: {json.dumps(verdict, indent=1)}")
    return verdict


#: Wall budget for the autoscale chaos run (fleet growth under genuine
#: overload + SIGKILL-resume + latency-window flush to the scale-down).
AUTOSCALE_TIMEOUT_S = 600

AUTOSCALER_DAEMON = os.path.join("tools", "autoscaler_daemon.py")


def _autoscaler_argv(
    exp_dir: str, url: str, up_p99_ms: float, down_p99_ms: float
) -> list[str]:
    return [
        sys.executable, "-u", os.path.join(REPO, AUTOSCALER_DAEMON),
        "--target", url,
        "--journal", os.path.join(exp_dir, "logs", "autoscale.jsonl"),
        "--telemetry", os.path.join(exp_dir, "logs", "telemetry.jsonl"),
        "--min-replicas", "1", "--max-replicas", "3",
        "--step-up", "2", "--step-down", "1",
        "--up-p99-ms", f"{up_p99_ms:.1f}",
        "--down-p99-ms", f"{down_p99_ms:.1f}",
        "--cooldown-s", "1.0", "--confirm-samples", "2",
        "--poll-interval-s", "0.25", "--settle-timeout-s", "120",
    ]


def _read_scale_journal(exp_dir: str) -> list[dict]:
    from howtotrainyourmamlpytorch_tpu.serve.resilience.promotion import (
        PromotionJournal,
    )

    return PromotionJournal.load(
        os.path.join(exp_dir, "logs", "autoscale.jsonl")
    )


def run_autoscale_chaos(workdir: str, verbose: bool = True) -> dict:
    """The self-driving fleet, end to end, zero intervention: a
    1-replica pool serves adapt-heavy overload while the autoscaler
    daemon CLI (its own process) watches the HTTP front door and drives
    the fleet through POST ``/admin/scale``. Faults, each mapping to
    its documented recovery:

    * ``autoscaler_kill_at_phase=1`` (daemon env) — the daemon is
      SIGKILLed with the scale-up DECIDED row journaled but the fleet
      untouched (the journal-then-act window): the restarted daemon
      replays the journal, journals ``resumed`` and re-issues the SAME
      target size — idempotent, so the fleet settles at 3 exactly once,
      no double-spawned replica;
    * ``replica_kill_at_request`` — one replica dies mid-stream under
      live traffic: the pool re-dispatches the request, the caller
      never sees it, the supervisor re-warms the slot;
    * organic load swing — thresholds are derived from measured probe
      latencies on THIS machine, the overload is genuinely slow
      (distinct support sets, every request pays the inner loop) and
      the idle phase genuinely fast (cache-hit flush), so both the
      scale-up and the scale-down decisions come from the policy
      reading real signals, not from stubbed metrics.

    Asserted outcome: >= 1 scale-up and >= 1 scale-down decided +
    settled, the SIGKILL resume exactly-once (no decision driven
    twice), the replica death recovered, and ZERO failed requests
    across every phase."""
    import threading as _threading

    from howtotrainyourmamlpytorch_tpu.serve import make_http_server
    from howtotrainyourmamlpytorch_tpu.serve.pool import (
        PoolConfig,
        ReplicaPool,
    )
    from howtotrainyourmamlpytorch_tpu.serve.resilience.promotion import (
        parse_prometheus,
    )
    from howtotrainyourmamlpytorch_tpu.serve.resilience.replica import (
        LocalReplica,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry import events as tel_events
    from howtotrainyourmamlpytorch_tpu.telemetry.events import EventLog
    from howtotrainyourmamlpytorch_tpu.utils import faultinject
    from tools.serve_loadtest import run_loadtest, synth_episodes

    def log(msg):
        if verbose:
            print(f"chaos: {msg}", file=sys.stderr, flush=True)

    cfg_path = tiny_config(workdir, "chaos_autoscale", devices=1)
    with open(cfg_path) as f:
        cfg = json.load(f)
    exp_dir = cfg["experiment_name"]
    os.makedirs(os.path.join(exp_dir, "logs"), exist_ok=True)
    telemetry_path = os.path.join(exp_dir, "logs", "telemetry.jsonl")

    previous_dataset_dir = os.environ.get("DATASET_DIR")
    os.environ["DATASET_DIR"] = workdir
    sink = EventLog(telemetry_path)
    previous_sink = tel_events.install(sink)
    from tools.serve_maml import build_learner

    learner = build_learner("maml", cfg_path)
    way = int(cfg["num_classes_per_set"])
    query = int(cfg["num_target_samples"])

    def factory(index: int) -> LocalReplica:
        import jax

        from howtotrainyourmamlpytorch_tpu.serve import (
            ServeConfig,
            ServingAPI,
        )

        api = ServingAPI(
            learner, learner.init_state(jax.random.PRNGKey(0)),
            # The overload phase holds adapt-heavy requests queued for
            # several inner-loop times on purpose; a 2s queue-age
            # degrade would shed them (failed requests), so the age
            # trip-wire is lifted out of the way — depth-based admission
            # (hard cap 64, soft 16) still bounds the queue, and the
            # harness caps in-flight below the soft limit.
            ServeConfig(
                meta_batch_size=2, max_wait_ms=0.0,
                max_queue_age_ms=60_000.0,
            ),
        )
        api.engine.warmup([(way, 1, query)])
        return LocalReplica(api, replica_id=f"local-{index}")

    pool = ReplicaPool(
        factory,
        PoolConfig(
            n_replicas=1, health_interval_s=0.1, restart_backoff_s=0.2,
            min_uptime_s=0.0, dispatch_timeout_s=60.0,
        ),
    )

    def pool_deaths() -> float:
        return parse_prometheus(pool.metrics_text()).get(
            "maml_serve_pool_replica_deaths_total", 0.0
        )

    daemon_holder: dict | None = None
    server = None
    flush_stop = _threading.Event()
    flush_lock = _threading.Lock()
    flush_counts = {"ok": 0, "err": 0}
    flush_threads: list = []
    overload_results: list[dict] = []
    verdict: dict = {"schedule": ["autoscale"], "ok": False}
    try:
        if not pool.wait_ready(timeout=300.0):
            raise RuntimeError("seed replica never became healthy")
        server = make_http_server(pool, "127.0.0.1", 0)
        port = server.server_address[1]
        url = f"http://127.0.0.1:{port}"
        server_thread = _threading.Thread(
            target=server.serve_forever, daemon=True
        )
        server_thread.start()
        log(f"pool front door on {url} (1 replica)")

        bb = learner.cfg.backbone
        image_shape = (bb.image_channels, bb.image_height, bb.image_width)
        flush_eps = synth_episodes(
            6, way=way, shot=1, query=query, image_shape=image_shape,
            seed=11,
        )

        # -- latency probes: policy thresholds from THIS machine --------
        def timed_classify(episode) -> float:
            xs, ys, xq = episode
            t0 = time.monotonic()
            pool.classify(xs, ys, xq, timeout=120.0)
            return (time.monotonic() - t0) * 1e3

        adapt_samples = [
            timed_classify(ep) for ep in synth_episodes(
                4, way=way, shot=1, query=query, image_shape=image_shape,
                seed=5,
            )
        ][1:]  # first sample may carry warmup stragglers
        timed_classify(flush_eps[0])  # pay its adapt once
        hit_samples = [timed_classify(flush_eps[0]) for _ in range(8)]
        adapt_ms = sorted(adapt_samples)[len(adapt_samples) // 2]
        hit_ms = sorted(hit_samples)[len(hit_samples) // 2]
        down_p99_ms = max(60.0, 6.0 * hit_ms)
        up_p99_ms = max(2.2 * down_p99_ms, 1.5 * adapt_ms)
        log(f"probes: adapt {adapt_ms:.0f}ms, cache-hit {hit_ms:.0f}ms "
            f"-> up above {up_p99_ms:.0f}ms, down below "
            f"{down_p99_ms:.0f}ms")

        # -- autoscaler daemon, armed to die inside the act window ------
        daemon_env = dict(os.environ)
        daemon_env["PYTHONPATH"] = REPO + os.pathsep + daemon_env.get(
            "PYTHONPATH", ""
        )
        daemon_env["JAX_PLATFORMS"] = "cpu"
        daemon_env["MAML_FAULTS"] = "autoscaler_kill_at_phase=1"
        argv = _autoscaler_argv(exp_dir, url, up_p99_ms, down_p99_ms)
        daemon_holder = {"proc": subprocess.Popen(
            argv, cwd=REPO, env=daemon_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )}
        log("autoscaler daemon started (autoscaler_kill_at_phase=1: "
            "SIGKILL with the decision journaled, fleet untouched)")
        t_deadline = time.time() + AUTOSCALE_TIMEOUT_S

        # -- overload: every request pays the inner loop ----------------
        # Distinct support sets keep the adapt path honest; max_workers
        # bounds in-flight below the soft admission limit so the p99
        # breach arrives WITHOUT a single shed request.
        burst = 0
        while time.time() < t_deadline:
            burst += 1
            burst_eps = synth_episodes(
                48, way=way, shot=1, query=query, image_shape=image_shape,
                seed=100 + burst,
            )
            overload_results.append(run_loadtest(
                pool, burst_eps, rate_qps=max(4.0, 3000.0 / adapt_ms),
                duration_s=6.0, p99_budget_ms=1e9, error_slo=0.0,
                timeout_s=120.0, seed=burst, max_workers=8,
                sample_health=False,
            ))
            if any(
                r["phase"] == "decided" for r in _read_scale_journal(exp_dir)
            ):
                break
        rows = _read_scale_journal(exp_dir)
        if not any(r["phase"] == "decided" for r in rows):
            raise RuntimeError(
                "overload never produced a journaled scale-up decision"
            )
        try:
            rc = daemon_holder["proc"].wait(timeout=60)
        except subprocess.TimeoutExpired as exc:
            raise RuntimeError(
                "daemon survived its armed kill point"
            ) from exc
        pre_resume = pool.healthz()
        verdict["daemon_sigkilled"] = rc in (-9, 137)
        verdict["fleet_untouched_at_kill"] = pre_resume["pool_size"] == 1
        log(f"daemon SIGKILLed pre-apply (rc {rc}); decided row journaled, "
            f"pool still size {pre_resume['pool_size']}")

        # -- restart clean: journal replay drives the scale-up once -----
        restart_env = dict(daemon_env)
        restart_env.pop("MAML_FAULTS", None)
        daemon_holder["proc"] = subprocess.Popen(
            argv, cwd=REPO, env=restart_env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        log("daemon restarted without faults: replaying the journal")
        settled_up = None
        while time.time() < t_deadline:
            rows = _read_scale_journal(exp_dir)
            settled = [r for r in rows if r["phase"] == "settled"]
            if settled:
                settled_up = settled[0]
                break
            time.sleep(0.3)
        if settled_up is None:
            raise RuntimeError("resumed scale-up never settled")
        verdict["resumed_settled_healthy"] = bool(settled_up.get("healthy"))
        post_up = pool.healthz()
        verdict["pool_size_after_up"] = post_up["pool_size"]
        log(f"scale-up settled exactly-once: pool {post_up['pool_size']} "
            f"replicas, {post_up['healthy_replicas']} healthy")

        # -- cache-hit flush + replica kill -> the scale-down -----------
        # The pool's latency summary keeps a bounded recent window, so
        # its p99 only falls once fast samples displace the overload's;
        # the flush IS the light-traffic tail after the spike. The 40th
        # flush request kills its replica mid-stream — the pool
        # re-dispatches, so the caller never sees it.
        deaths_before = pool_deaths()
        faultinject.activate(
            faultinject.FaultPlan(replica_kill_at_request=40)
        )

        def flush_worker(start: int) -> None:
            i = start
            while not flush_stop.is_set():
                xs, ys, xq = flush_eps[i % len(flush_eps)]
                i += 1
                try:
                    pool.classify(xs, ys, xq, timeout=60.0)
                    key = "ok"
                except Exception:  # noqa: BLE001 — any failure fails the verdict
                    key = "err"
                with flush_lock:
                    flush_counts[key] += 1

        flush_threads = [
            _threading.Thread(target=flush_worker, args=(w,), daemon=True)
            for w in range(6)
        ]
        for t in flush_threads:
            t.start()
        down_settled = None
        while time.time() < t_deadline:
            rows = _read_scale_journal(exp_dir)
            decided_down = {
                r["decision_id"] for r in rows
                if r["phase"] == "decided"
                and r.get("to_size", 0) < r.get("from_size", 0)
            }
            down_settled = next(
                (r for r in rows if r["phase"] == "settled"
                 and r["decision_id"] in decided_down),
                None,
            )
            if down_settled is not None:
                break
            time.sleep(0.5)
        flush_stop.set()
        for t in flush_threads:
            t.join(timeout=60)
        faultinject.deactivate()
        if down_settled is None:
            raise RuntimeError(
                "cache-hit flush never produced a settled scale-down"
            )
        verdict["replica_deaths"] = int(pool_deaths() - deaths_before)
        log(f"scale-down settled ({down_settled['decision_id']} -> "
            f"{down_settled['to_size']} replicas); flush "
            f"{flush_counts['ok']} ok / {flush_counts['err']} failed; "
            f"replica deaths {verdict['replica_deaths']}")
    finally:
        flush_stop.set()
        try:
            faultinject.deactivate()
        except Exception:  # noqa: BLE001
            pass
        if daemon_holder is not None:
            proc = daemon_holder.get("proc")
            if proc is not None:
                proc.terminate()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
        for t in flush_threads:
            t.join(timeout=10)
        if server is not None:
            server.shutdown()
            server.server_close()
            server_thread.join(timeout=10)
        pool.close()
        tel_events.install(previous_sink)
        sink.flush()
        if previous_dataset_dir is None:
            os.environ.pop("DATASET_DIR", None)
        else:
            os.environ["DATASET_DIR"] = previous_dataset_dir

    # -- verdict --------------------------------------------------------
    rows = _read_scale_journal(exp_dir)
    events = _read_events(exp_dir)
    decided = [r for r in rows if r["phase"] == "decided"]
    ups = [r for r in decided if r["to_size"] > r["from_size"]]
    downs = [r for r in decided if r["to_size"] < r["from_size"]]
    resumed_rows = [r for r in rows if r["phase"] == "resumed"]
    # Exactly-once across the SIGKILL: per decision at most one settled
    # row, and a duplicate applied row only when a resume re-drove it.
    by_id: dict[str, list[dict]] = {}
    for r in rows:
        if r.get("decision_id"):
            by_id.setdefault(r["decision_id"], []).append(r)
    double_driven = []
    for did, drows in by_id.items():
        n_settled = sum(1 for r in drows if r["phase"] == "settled")
        applied = [r for r in drows if r["phase"] == "applied"]
        if n_settled > 1 or (
            len(applied) > 1
            and not any(r.get("resumed") for r in applied)
        ):
            double_driven.append(did)
    offered = sum(r["offered"] for r in overload_results) + sum(
        flush_counts.values()
    )
    ok_requests = (
        sum(r["completed_ok"] for r in overload_results)
        + flush_counts["ok"]
    )
    verdict.update({
        "devices": 1,
        "scale_ups": len(ups),
        "scale_downs": len(downs),
        "resumed_rows": len(resumed_rows),
        "settled_rows": sum(1 for r in rows if r["phase"] == "settled"),
        "double_driven": double_driven,
        "requests_offered": offered,
        "requests_ok": ok_requests,
        "requests_failed": offered - ok_requests,
        "autoscale_event_types": sorted({
            e["type"] for e in events
            if str(e.get("type", "")).startswith("autoscale")
        }),
        "ok": bool(
            verdict.get("daemon_sigkilled")
            and verdict.get("fleet_untouched_at_kill")
            and ups
            and downs
            and resumed_rows
            and verdict.get("resumed_settled_healthy")
            and not double_driven
            and verdict.get("replica_deaths", 0) >= 1
            and offered > 0
            and offered == ok_requests
        ),
    })
    if not verdict["ok"] and verbose:
        log(f"verdict: {json.dumps(verdict, indent=1)}")
    return verdict


def measure_multihost_recovery(seed: int = 0) -> dict:
    """Bench hook behind the ``multihost_recovery_s`` standard-emission
    key: one kill-a-host chaos run through the real dispatcher CLI on a
    synthesized tiny dataset."""
    workdir = tempfile.mkdtemp(prefix="chaos_killhost_")
    try:
        make_tiny_dataset(os.path.join(workdir, "omniglot_mini"), seed=seed)
        verdict = run_killhost_chaos(workdir, verbose=False)
        return {"value": verdict["multihost_recovery_s"], "verdict": verdict}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def measure_recovery(budget_s: float = 240.0, seed: int = 0) -> dict:
    """Bench hook behind the ``train_recovery_s`` standard-emission key:
    one SIGTERM preemption driven through the real CLI on a synthesized
    tiny dataset; returns ``{"value": seconds, "verdict": ...}``."""
    del budget_s  # the tiny run is bounded by PHASE_TIMEOUT_S per phase
    workdir = tempfile.mkdtemp(prefix="chaos_recovery_")
    try:
        make_tiny_dataset(
            os.path.join(workdir, "omniglot_mini"), seed=seed
        )
        verdict = run_chaos(
            workdir, ["sigterm"], devices=1, baseline=False, verbose=False
        )
        return {"value": verdict["train_recovery_s"], "verdict": verdict}
    finally:
        shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--tiny", action="store_true",
                        help="synthesize the tiny dataset + config in a "
                             "temp workdir (the only supported mode)")
    parser.add_argument("--schedule", default="auto",
                        help="comma-separated fault classes "
                             f"{FAULT_CLASSES}, 'auto' (seeded shuffle of "
                             "all six), 'killhost' (alone: SIGKILL one "
                             "worker of a 2-process fleet driven through "
                             "the dispatcher — the host-loss class), or "
                             "'promote' (alone: the continuous train→serve "
                             "loop — trainer + promotion daemon + "
                             "2-replica pool + loadtest through automatic "
                             "promotions, corrupt-candidate rejection and "
                             "a forced SLO rollback), or 'autoscale' "
                             "(alone: the self-driving fleet — autoscaler "
                             "daemon + 1->3->2 replica pool under a "
                             "measured load swing, SIGKILLed mid-scale-up "
                             "and resumed exactly-once from its journal)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--devices", type=int, default=1,
                        help="virtual CPU mesh devices (dp extent); hangs "
                             "degrade it like the dispatcher would")
    parser.add_argument("--baseline", action="store_true",
                        help="also run an unfaulted twin and assert "
                             "bit-exact final params (exact-path "
                             "schedules only)")
    parser.add_argument("--json", action="store_true",
                        help="verdict JSON only on stdout")
    parser.add_argument("--workdir", default=None,
                        help="keep state here instead of a temp dir")
    args = parser.parse_args(argv)

    if not args.tiny and args.workdir is None:
        parser.error("--tiny is required (or provide --workdir with a "
                     "prepared dataset)")
    if args.schedule == "auto":
        schedule = list(FAULT_CLASSES)
        random.Random(args.seed).shuffle(schedule)
    else:
        schedule = [s.strip() for s in args.schedule.split(",") if s.strip()]

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_train_")
    cleanup = args.workdir is None
    try:
        dataset = os.path.join(workdir, "omniglot_mini")
        if not os.path.isdir(dataset):
            make_tiny_dataset(dataset, seed=args.seed)
        if schedule == ["killhost"]:
            # Kill-a-host runs through the DISPATCHER (the host-loss
            # supervisor), not the bare entry point — structurally its own
            # harness; combine with other classes by running twice.
            verdict = run_killhost_chaos(workdir, verbose=not args.json)
        elif "killhost" in schedule:
            parser.error("killhost runs alone: --schedule killhost")
        elif schedule == ["promote"]:
            # The continuous train→serve loop: trainer + promotion daemon
            # + 2-replica pool + loadtest concurrently, through >= 3
            # automatic promotions, one corrupt-candidate rejection and
            # one forced post-promotion rollback — its own harness.
            verdict = run_promote_chaos(workdir, verbose=not args.json)
        elif "promote" in schedule:
            parser.error("promote runs alone: --schedule promote")
        elif schedule == ["autoscale"]:
            # The self-driving fleet: autoscaler daemon + replica pool
            # under a measured overload/idle swing, through a SIGKILL
            # inside the journal-then-act window, one replica death and
            # a settled scale-down — its own harness.
            verdict = run_autoscale_chaos(workdir, verbose=not args.json)
        elif "autoscale" in schedule:
            parser.error("autoscale runs alone: --schedule autoscale")
        else:
            verdict = run_chaos(
                workdir, schedule, devices=args.devices,
                baseline=args.baseline, verbose=not args.json,
            )
        print(json.dumps(verdict))
        return 0 if verdict["ok"] else 2
    finally:
        if cleanup:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
