"""Mechanical keep/revert/regress judge over the bench trajectory.

The repo carries its perf story as checked-in ``BENCH_*.json`` emissions
plus keep/revert prose tables in PERF_NOTES.md. This tool is the ROADMAP's
"self-judging keep/revert harness": the tables live as DATA in
``tools/bench_gates.json`` (one entry per bench key: gate expression,
lever flag, regression tolerance, pending-until-TPU marker), and the judge
applies them mechanically over the full trajectory::

    python -m tools.bench_judge                 # human table
    python -m tools.bench_judge --json          # machine-readable
    python -m tools.bench_judge --trajectory BENCH_r0*.json
    python -m tools.bench_judge --explain KEY   # one gate, full history

Per gated key, one verdict:

* ``keep``    — the key's gate expression holds on the latest accepted run
                (or the key has no gate and is regression-tracked only);
* ``revert``  — the gate expression is in force and FAILS: the lever
                missed its bar, leave its flag unflipped;
* ``regress`` — the latest accepted value is worse than the LAST ACCEPTED
                run's beyond the key's tolerance — a perf claim rotted.
                The judge exits non-zero iff any key regresses, and
                ``tests/test_bench_judge.py`` runs it in tier-1, so a
                regression can never land silently;
* ``pending`` — the key awaits its first capture (absent/null in the
                latest accepted emission), its gate only comes into force
                on a future run (``gate_from_run`` — the lever shipped
                after the last quiet-chip capture), or its gate references
                a key that has no measurement yet.

The contention sentinel is honored end to end: an emission self-labeled
``"contended": true`` is never the accepted baseline and is never judged —
a poisoned number can neither pass a gate nor manufacture a regression.

Stale-key detection (the ROADMAP's "stops stale flags from accumulating"
clause): the judge lists gate keys absent from the latest emission, gate
keys ``bench.py`` no longer declares (``EMITTED_KEYS``, read by AST parse
— no jax import), and emitted keys with neither a gate nor an explicit
``ungated_ok`` entry — so bench key drift is caught at review time, not on
the next TPU session.
"""

from __future__ import annotations

import argparse
import ast
import glob
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(_HERE)
DEFAULT_GATES_PATH = os.path.join(_HERE, "bench_gates.json")
GATES_SCHEMA = 1

#: Severity order of the human table (and of the summary counts).
VERDICT_ORDER = ("regress", "revert", "pending", "keep")

#: AST node classes a gate expression may use — names, numeric constants,
#: arithmetic, comparisons, boolean combinators. Anything else (calls,
#: subscripts, attributes) is a malformed gate and raises.
_ALLOWED_NODES = (
    ast.Expression, ast.Compare, ast.BinOp, ast.UnaryOp, ast.BoolOp,
    ast.Name, ast.Constant, ast.Load,
    ast.Add, ast.Sub, ast.Mult, ast.Div,
    ast.USub, ast.UAdd,
    ast.Lt, ast.LtE, ast.Gt, ast.GtE, ast.Eq, ast.NotEq,
    ast.And, ast.Or,
)


def eval_gate(expr: str, env: dict) -> bool | None:
    """Evaluates a restricted gate expression against one emission's keys
    (``this`` = the judged key's own value). Returns ``None`` when any
    referenced name has no measurement yet — the gate is not evaluable,
    which judges as ``pending``, never as a pass. Raises ``ValueError`` on
    an expression outside the restricted grammar (a malformed gates file
    must fail loudly, not judge wrongly)."""
    tree = ast.parse(expr, mode="eval")
    names = set()
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise ValueError(
                f"gate expression {expr!r} uses disallowed syntax "
                f"({type(node).__name__})"
            )
        if isinstance(node, ast.Name):
            names.add(node.id)
        if isinstance(node, ast.Constant) and not isinstance(
            node.value, (int, float)
        ):
            raise ValueError(
                f"gate expression {expr!r} uses a non-numeric constant"
            )
    scope = {}
    for name in names:
        value = _numeric(env.get(name))
        if value is None:
            return None
        scope[name] = value
    return bool(
        eval(  # noqa: S307 — AST-whitelisted grammar, empty builtins
            compile(tree, "<bench-gate>", "eval"), {"__builtins__": {}}, scope
        )
    )


def _numeric(value) -> float | None:
    """Bench values usable in gates/regression math: numbers and bools
    (True == 1.0). Strings, lists, null, NaN -> None."""
    if isinstance(value, bool):
        return float(value)
    if isinstance(value, (int, float)):
        value = float(value)
        return value if value == value else None
    return None


def load_gates(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if int(doc.get("schema", -1)) > GATES_SCHEMA:
        raise ValueError(
            f"{path}: gates schema {doc.get('schema')} is newer than this "
            f"judge reads (up to {GATES_SCHEMA})"
        )
    if not isinstance(doc.get("gates"), dict):
        raise ValueError(f"{path}: no 'gates' mapping")
    return doc


def load_trajectory(paths: list[str]) -> list[dict]:
    """Loads the emission files in order. Accepts both the driver wrapper
    layout (``{"n": ..., "parsed": {...}}`` — the checked-in BENCH_r*.json)
    and a raw one-line emission payload (what ``bench.py`` prints). Runs
    without an ``n`` are numbered by position."""
    runs = []
    for index, path in enumerate(paths):
        with open(path) as f:
            doc = json.load(f)
        if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
            parsed = doc["parsed"]
            n = int(doc.get("n", index + 1))
        elif isinstance(doc, dict):
            parsed, n = doc, index + 1
        else:
            raise ValueError(f"{path}: not a bench emission")
        runs.append({
            "name": os.path.basename(path),
            "n": n,
            "parsed": parsed,
            "contended": bool(parsed.get("contended", False)),
        })
    runs.sort(key=lambda run: run["n"])
    return runs


def bench_emitted_keys(bench_path: str | None = None) -> tuple | None:
    """``bench.EMITTED_KEYS`` read by AST parse — no jax import, so the
    judge stays a sub-second stdlib tool. ``None`` when bench.py is absent
    or carries no literal declaration (the judge then skips the
    declaration cross-check and judges from emissions alone)."""
    path = bench_path or os.path.join(REPO, "bench.py")
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "EMITTED_KEYS"
        ):
            try:
                return tuple(ast.literal_eval(node.value))
            except ValueError:
                return None
    return None


def program_registry_names(common_path: str | None = None) -> tuple | None:
    """``models/common.PROGRAM_REGISTRY_NAMES`` read by AST parse — the
    registered-program name table, by the same jax-free mechanism as
    ``EMITTED_KEYS``. A gate whose ``source`` is ``programs:<name>`` is
    judged against this table: the program disappearing from the registry
    makes the gate STALE exactly like a key dropped from bench's
    emission. ``None`` when the module is absent or the table is not a
    literal (the cross-check is then skipped)."""
    path = common_path or os.path.join(
        REPO, "howtotrainyourmamlpytorch_tpu", "models", "common.py"
    )
    try:
        with open(path) as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "PROGRAM_REGISTRY_NAMES"
        ):
            try:
                return tuple(ast.literal_eval(node.value))
            except ValueError:
                return None
    return None


def _regressed(direction: str, value: float, prior: float,
               tolerance: float, abs_slack: float) -> bool:
    slack = max(abs(prior) * tolerance, abs_slack)
    if direction == "lower":
        return value > prior + slack
    return value < prior - slack


def _prior_value(key: str, accepted: list[dict]) -> tuple:
    """Newest earlier accepted run carrying a numeric value for ``key`` —
    the "last accepted run" a regression is judged against."""
    for run in reversed(accepted[:-1]):
        value = _numeric(run["parsed"].get(key))
        if value is not None:
            return value, run["name"]
    return None, None


def judge(gates_doc: dict, runs: list[dict]) -> dict:
    """Applies every gate over the trajectory; returns the result document
    (the ``--json`` schema)."""
    accepted = [run for run in runs if not run["contended"]]
    if not accepted:
        raise ValueError(
            "no accepted (sentinel-clean) emission in the trajectory — "
            "every run is contended; nothing can be judged"
        )
    latest = accepted[-1]
    default_tolerance = float(gates_doc.get("default_tolerance", 0.08))
    gates = gates_doc["gates"]
    ungated_ok = set(gates_doc.get("ungated_ok", []))
    emitted = bench_emitted_keys()

    verdicts: dict[str, dict] = {}
    for key, spec in gates.items():
        direction = str(spec.get("direction", "higher"))
        tolerance = float(spec.get("tolerance", default_tolerance))
        abs_slack = float(spec.get("abs_slack", 0.0))
        gate_expr = spec.get("gate")
        gate_from_run = spec.get("gate_from_run")
        value = _numeric(latest["parsed"].get(key))
        prior, prior_run = _prior_value(key, accepted)
        entry = {
            "value": value,
            "prior": prior,
            "prior_run": prior_run,
            "gate": gate_expr,
            "lever": spec.get("lever"),
            "source": spec.get("source", "bench.py"),
            "reason": "",
        }
        if (
            value is not None
            and prior is not None
            and _regressed(direction, value, prior, tolerance, abs_slack)
        ):
            entry["verdict"] = "regress"
            entry["reason"] = (
                f"{value:g} is worse than the last accepted run's "
                f"{prior:g} ({prior_run}) beyond tolerance "
                f"{tolerance:g}/{abs_slack:g}"
            )
        elif value is None:
            entry["verdict"] = "pending"
            entry["reason"] = (
                "no measurement in the latest accepted emission "
                f"({latest['name']})"
            )
        elif gate_from_run is not None and latest["n"] < int(gate_from_run):
            entry["verdict"] = "pending"
            entry["reason"] = (
                f"gate in force from run {int(gate_from_run)} (lever "
                f"shipped after run {latest['n']}); awaiting the next "
                "quiet-chip capture"
            )
        elif gate_expr:
            ok = eval_gate(gate_expr, {**latest["parsed"], "this": value})
            if ok is None:
                entry["verdict"] = "pending"
                entry["reason"] = (
                    "gate references key(s) with no measurement yet"
                )
            elif ok:
                entry["verdict"] = "keep"
                entry["reason"] = f"gate holds on {latest['name']}"
            else:
                entry["verdict"] = "revert"
                entry["reason"] = (
                    f"gate fails on {latest['name']}: leave the lever "
                    "unflipped"
                )
        else:
            entry["verdict"] = "keep"
            entry["reason"] = "regression-tracked; no A/B bar"
        verdicts[key] = entry

    counts = {name: 0 for name in VERDICT_ORDER}
    for entry in verdicts.values():
        counts[entry["verdict"]] += 1

    # Stale-key detection (bench key drift caught at review time).
    missing_from_latest = sorted(
        key for key in gates if key not in latest["parsed"]
    )
    stale_gates = (
        sorted(
            key for key, spec in gates.items()
            if spec.get("source", "bench.py") == "bench.py"
            and key not in emitted
        )
        if emitted is not None
        else []
    )
    # Program-derived gates (source "programs:<registered name>") go
    # stale when models/common.py no longer registers the named program —
    # the registry table is the declaration surface, exactly as
    # EMITTED_KEYS is for bench-emitted keys.
    registry = program_registry_names()
    if registry is not None:
        stale_gates = sorted(set(stale_gates) | {
            key for key, spec in gates.items()
            if str(spec.get("source", "")).startswith("programs:")
            and str(spec["source"]).split(":", 1)[1] not in registry
        })
    known = set(gates) | ungated_ok
    emission_keys = set(latest["parsed"]) | set(emitted or ())
    ungated_keys = sorted(emission_keys - known)

    return {
        "schema": GATES_SCHEMA,
        "trajectory": [run["name"] for run in runs],
        "accepted_run": latest["name"],
        "accepted_n": latest["n"],
        "skipped_contended": [
            run["name"] for run in runs if run["contended"]
        ],
        "verdicts": verdicts,
        "counts": counts,
        "regressions": sorted(
            key for key, entry in verdicts.items()
            if entry["verdict"] == "regress"
        ),
        "stale": {
            "missing_from_latest": missing_from_latest,
            "stale_gates": stale_gates,
            "ungated_keys": ungated_keys,
        },
    }


def explain(gates_doc: dict, runs: list[dict], key: str) -> dict:
    """One key, fully accounted for: the gate's provenance (source, lever,
    expression, tolerances, pending-until marker) plus the verdict the
    judge would have returned after EVERY prefix of the trajectory — the
    key's whole history, not just today's verdict. Contended runs appear
    in the history as skipped, exactly as the judge treats them."""
    gates = gates_doc["gates"]
    if key not in gates:
        ungated_ok = set(gates_doc.get("ungated_ok", []))
        where = (
            "listed in ungated_ok (deliberately carries no gate)"
            if key in ungated_ok else "not in the gates file at all"
        )
        raise ValueError(f"no gate entry for {key!r} — {where}")
    spec = gates[key]
    default_tolerance = float(gates_doc.get("default_tolerance", 0.08))

    history = []
    for end in range(len(runs)):
        run = runs[end]
        if run["contended"]:
            history.append({
                "n": run["n"], "run": run["name"],
                "value": _numeric(run["parsed"].get(key)),
                "verdict": "skipped", "reason": "contended emission",
            })
            continue
        # Judge the prefix ending here: the verdict this run produced
        # when it WAS the latest accepted emission.
        entry = judge(gates_doc, runs[:end + 1])["verdicts"][key]
        history.append({
            "n": run["n"], "run": run["name"],
            "value": entry["value"],
            "verdict": entry["verdict"], "reason": entry["reason"],
        })

    return {
        "key": key,
        "source": spec.get("source", "bench.py"),
        "lever": spec.get("lever"),
        "gate": spec.get("gate"),
        "direction": str(spec.get("direction", "higher")),
        "tolerance": float(spec.get("tolerance", default_tolerance)),
        "abs_slack": float(spec.get("abs_slack", 0.0)),
        "gate_from_run": spec.get("gate_from_run"),
        "perf_notes": spec.get("perf_notes"),
        "note": spec.get("note"),
        "history": history,
        "current": history[-1] if history else None,
    }


def render_explain(result: dict) -> str:
    lines = [f"bench judge — {result['key']}"]
    lines.append(f"  source:    {result['source']}")
    if result["lever"]:
        lines.append(f"  lever:     {result['lever']}")
    if result["gate"]:
        qualifier = (
            f" (in force from run {int(result['gate_from_run'])})"
            if result["gate_from_run"] is not None else ""
        )
        lines.append(f"  gate:      {result['gate']}{qualifier}")
    else:
        lines.append("  gate:      none — regression-tracked only")
    lines.append(
        f"  regression bar: direction {result['direction']}, tolerance "
        f"{result['tolerance']:g} of the last accepted value"
        + (f" (+{result['abs_slack']:g} absolute)"
           if result["abs_slack"] else "")
    )
    if result["perf_notes"]:
        lines.append(f"  perf_notes: §{result['perf_notes']}")
    if result["note"]:
        lines.append(f"  note:      {result['note']}")
    lines.append("")
    lines.append(f"  {'n':>3} {'run':<28} {'value':>12} {'verdict':<8} reason")
    lines.append("  " + "-" * 76)
    for row in result["history"]:
        value = "—" if row["value"] is None else f"{row['value']:g}"
        lines.append(
            f"  {row['n']:>3} {row['run']:<28} {value:>12} "
            f"{row['verdict']:<8} {row['reason']}"
        )
    current = result["current"]
    if current is not None:
        lines.append("")
        lines.append(
            f"  current: {current['verdict']} — {current['reason']}"
        )
    return "\n".join(lines)


def render_text(result: dict) -> str:
    lines = []
    lines.append(
        f"bench judge — trajectory {', '.join(result['trajectory'])}; "
        f"accepted baseline {result['accepted_run']} "
        f"(run {result['accepted_n']})"
    )
    if result["skipped_contended"]:
        lines.append(
            "contention sentinel: skipped "
            + ", ".join(result["skipped_contended"])
        )
    lines.append("")
    header = (
        f"  {'verdict':<8} {'key':<48} {'value':>12} {'prior':>12}  reason"
    )
    lines.append(header)
    lines.append("  " + "-" * (len(header) + 20))

    def fmt(value):
        return "—" if value is None else f"{value:g}"

    for verdict in VERDICT_ORDER:
        for key, entry in sorted(result["verdicts"].items()):
            if entry["verdict"] != verdict:
                continue
            lines.append(
                f"  {verdict:<8} {key:<48} {fmt(entry['value']):>12} "
                f"{fmt(entry['prior']):>12}  {entry['reason']}"
            )
    counts = result["counts"]
    lines.append("")
    lines.append(
        "  " + ", ".join(f"{counts[name]} {name}" for name in VERDICT_ORDER)
    )
    stale = result["stale"]
    if stale["stale_gates"]:
        lines.append(
            "  STALE GATES (bench.py no longer emits): "
            + ", ".join(stale["stale_gates"])
        )
    if stale["ungated_keys"]:
        lines.append(
            "  UNGATED bench keys (add to bench_gates.json gates or "
            "ungated_ok): " + ", ".join(stale["ungated_keys"])
        )
    if stale["missing_from_latest"]:
        lines.append(
            "  gate keys absent from the latest emission (await capture): "
            + ", ".join(stale["missing_from_latest"])
        )
    if result["regressions"]:
        lines.append(
            "  REGRESSIONS: " + ", ".join(result["regressions"])
            + " — exit non-zero"
        )
    return "\n".join(lines)


def default_trajectory() -> list[str]:
    return sorted(glob.glob(os.path.join(REPO, "BENCH_*.json")))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Judge the checked-in bench trajectory against "
        "tools/bench_gates.json: keep/revert/regress/pending per key; "
        "exits non-zero iff any key regressed"
    )
    parser.add_argument(
        "--trajectory", nargs="+", metavar="BENCH_JSON",
        help="emission files oldest-first (default: BENCH_*.json in the "
             "repo root, sorted)",
    )
    parser.add_argument("--gates", default=DEFAULT_GATES_PATH,
                        help="gate data (default: tools/bench_gates.json)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable result instead of the table")
    parser.add_argument("--explain", metavar="KEY", default=None,
                        help="drill into one gate: provenance (source, "
                        "lever, expression, tolerances) + the verdict "
                        "history over every run of the trajectory")
    opts = parser.parse_args(argv)

    paths = opts.trajectory or default_trajectory()
    if not paths:
        print("bench_judge: no BENCH_*.json trajectory found",
              file=sys.stderr)
        return 2
    try:
        gates_doc = load_gates(opts.gates)
        runs = load_trajectory(paths)
        if opts.explain:
            result = explain(gates_doc, runs, opts.explain)
            print(json.dumps(result) if opts.json
                  else render_explain(result))
            return 0
        result = judge(gates_doc, runs)
    except (OSError, ValueError) as exc:
        print(f"bench_judge: {exc}", file=sys.stderr)
        return 2
    print(json.dumps(result) if opts.json else render_text(result))
    return 1 if result["regressions"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
