"""Steady-state step analysis for the flagship MAML++ program (VERDICT r2
weak #3 / next #4): quantitative dispatch/transfer/compute breakdown plus an
optional jax.profiler trace capture.

Usage: python tools/profile_step.py [--trace profiles/flagship]

Prints (quiet chip, shipped u8 wire):
  * compiled-program cost analysis: FLOPs/iter, HBM bytes/iter
  * measured per-iter wall time at K=25 scan dispatch
  * roofline bounds: MXU-bound time (flops/peak), HBM-bound time
    (bytes/bandwidth) -> which resource the step is actually limited by
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

V5E_PEAK_BF16_FLOPS = 394e12
V5E_PEAK_F32MULT_FLOPS = 197.4e12  # bench.py's MFU denominator
V5E_HBM_BYTES_PER_S = 819e9


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--trace", default="")
    parser.add_argument("--k", type=int, default=25)
    args = parser.parse_args()

    import dataclasses

    from __graft_entry__ import _episode_batch, _flagship_config
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
    from howtotrainyourmamlpytorch_tpu.models.common import WireCodec

    cfg = dataclasses.replace(
        _flagship_config(), wire_codec=WireCodec(1.0, None, None)
    )
    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    K = args.k
    batches = [_episode_batch(8, cfg, rng) for _ in range(K)]
    epoch = 20  # steady-state variant: second order, past the MSL horizon

    lowered = learner.lowered_train_iters(state, batches, epoch)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, list):
        cost = cost[0]
    flops_iter = float(cost.get("flops", 0.0)) / K
    bytes_iter = float(cost.get("bytes accessed", 0.0)) / K
    print(f"flops/iter          : {flops_iter:.3e}")
    print(f"hbm bytes/iter      : {bytes_iter:.3e}")

    # Wire bytes per iter (uint8 images + int32 labels).
    xs, xt, ys, yt = learner._prepare_batch(batches[0])
    wire = sum(a.nbytes for a in (xs, xt, ys, yt))
    print(f"wire bytes/iter     : {wire:.3e} (u8) "
          f"/ {4 * (xs.size + xt.size) + ys.nbytes + yt.nbytes:.3e} (f32)")

    # Measured steady-state rate.
    state, _ = learner.run_train_iters(state, batches, epoch=epoch)
    jax.block_until_ready(state.theta)
    t0 = time.perf_counter()
    reps = 40
    for _ in range(reps):
        state, _ = learner.run_train_iters(state, batches, epoch=epoch)
    jax.block_until_ready(state.theta)
    dt = time.perf_counter() - t0
    per_iter = dt / (reps * K)
    print(f"measured wall/iter  : {per_iter*1e6:.1f} us "
          f"({reps*K/dt:.0f} meta-iters/s)")

    mxu = flops_iter / V5E_PEAK_F32MULT_FLOPS
    hbm = bytes_iter / V5E_HBM_BYTES_PER_S
    print(f"mxu-bound time/iter : {mxu*1e6:.1f} us "
          f"({100*mxu/per_iter:.1f}% of measured)")
    print(f"hbm-bound time/iter : {hbm*1e6:.1f} us "
          f"({100*hbm/per_iter:.1f}% of measured)")
    slack = per_iter - max(mxu, hbm)
    print(f"latency slack/iter  : {slack*1e6:.1f} us "
          "(neither-MXU-nor-HBM: kernel launch/serialization overhead)")

    if args.trace:
        jax.profiler.start_trace(args.trace)
        for _ in range(3):
            state, _ = learner.run_train_iters(state, batches, epoch=epoch)
        jax.block_until_ready(state.theta)
        jax.profiler.stop_trace()
        print(f"trace written to {args.trace}")


if __name__ == "__main__":
    main()
