"""Steady-state step analysis for a MAML++ training program (VERDICT r2
weak #3 / r3 next #2): quantitative dispatch/transfer/compute breakdown plus
an optional jax.profiler trace capture.

Usage:
  python tools/profile_step.py [--config flagship|imagenet]
                               [--batch N] [--compute-dtype bfloat16]
                               [--lane-pad] [--task-chunk N]
                               [--fused-train] [--fused-pool]
                               [--conv-layout NHWC] [--k K]
                               [--trace profiles/flagship]

Prints (quiet chip, shipped u8 wire):
  * compiled-program cost analysis: FLOPs/iter, HBM bytes/iter
  * measured per-iter wall time at K-scan dispatch
  * roofline bounds: MXU-bound time (flops/peak), HBM-bound time
    (bytes/bandwidth) -> which resource the step is actually limited by

``--config imagenet`` profiles the mini-ImageNet north-star shapes
(84x84x3, 48 filters, 4 max-pool blocks, batch 2, 5-shot/15-target — the
configuration `mini-imagenet_maml++-mini-imagenet_5_2_0.01_48_5_0.json`
trains under).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

# v5e matmul peak, 197.4 TF/s: applies to bf16 inputs and to f32 inputs
# under XLA's `default` precision (bf16 multiplies). bench.py's MFU
# denominator. (394 TF/s is the chip's int8 rate, not a float peak.)
V5E_PEAK_F32MULT_FLOPS = 197.4e12
V5E_HBM_BYTES_PER_S = 819e9


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--trace", default="")
    parser.add_argument("--k", type=int, default=25)
    parser.add_argument("--device-prefetch", type=int, default=0,
                        help="stage dispatch groups through the device-side "
                             "async prefetcher (data/device_prefetch.py) at "
                             "this depth, so quiet-chip traces show the "
                             "staged vs unstaged path (0 = unstaged)")
    parser.add_argument("--config", default="flagship",
                        choices=["flagship", "imagenet"])
    parser.add_argument("--batch", type=int, default=0,
                        help="meta-batch size (0 = the config's own: "
                             "flagship 8, imagenet 2)")
    parser.add_argument("--compute-dtype", default="",
                        help="override compute dtype (e.g. bfloat16)")
    parser.add_argument("--conv-layout", default="",
                        choices=["", "NCHW", "NHWC"],
                        help="override ops.conv layout experiment switch")
    parser.add_argument("--no-remat", action="store_true",
                        help="disable per-inner-step rematerialization "
                             "(trades HBM for fewer recomputed forwards)")
    parser.add_argument("--fused-train", action="store_true",
                        help="enable the second-order-capable fused Pallas "
                             "norm on the train path (fused_norm_train; "
                             "ops/pallas_fused_norm.fused_bn_leaky_relu_ho)")
    parser.add_argument("--fused-pool", action="store_true",
                        help="also fuse the 2x2 max-pool epilogue into the "
                             "norm kernel on even-sized stages "
                             "(fused_norm_pool; implies a fused variant)")
    parser.add_argument("--lane-pad", action="store_true",
                        help="lane-padded compute layout (lane_pad_channels; "
                             "ops/layout.py): conv channels padded to the "
                             "128-lane-friendly width, 48 -> 64 at the "
                             "imagenet shapes")
    parser.add_argument("--task-chunk", type=int, default=0,
                        help="scan the meta-batch in task chunks of N "
                             "instead of one vmap (task_chunk; bounds live "
                             "activations — the HBM-spill lever). 0 = off")
    args = parser.parse_args()

    import dataclasses

    from __graft_entry__ import _episode_batch, _flagship_config
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
    from howtotrainyourmamlpytorch_tpu.models.common import WireCodec

    if args.config == "imagenet":
        from bench import _imagenet_shape_config

        cfg = dataclasses.replace(
            _imagenet_shape_config(),
            wire_codec=WireCodec(255.0, None, None),
        )
        batch_size = args.batch or 2
        shots, targets = 5, 15  # the config's 5-shot/15-target episodes
    else:
        cfg = dataclasses.replace(
            _flagship_config(), wire_codec=WireCodec(1.0, None, None)
        )
        batch_size = args.batch or 8
        shots, targets = 1, 1
    if args.compute_dtype:
        cfg = dataclasses.replace(cfg, compute_dtype=args.compute_dtype)
    if args.no_remat:
        cfg = dataclasses.replace(cfg, remat_inner_steps=False)
    if args.fused_train or args.fused_pool:
        cfg = dataclasses.replace(
            cfg,
            backbone=dataclasses.replace(
                cfg.backbone,
                fused_norm_train=True,
                fused_norm_pool=args.fused_pool,
            ),
        )
    if args.lane_pad:
        cfg = dataclasses.replace(
            cfg,
            backbone=dataclasses.replace(cfg.backbone, lane_pad_channels=True),
        )
    if args.task_chunk:
        cfg = dataclasses.replace(cfg, task_chunk=args.task_chunk)
    if args.conv_layout:
        from howtotrainyourmamlpytorch_tpu.ops import conv as conv_ops

        conv_ops.set_conv_layout(args.conv_layout)

    learner = MAMLFewShotLearner(cfg)
    state = learner.init_state(jax.random.PRNGKey(0))
    rng = np.random.RandomState(1)
    K = args.k
    batches = [
        _episode_batch(batch_size, cfg, rng, shots, targets) for _ in range(K)
    ]
    epoch = 20  # steady-state variant: second order, past the MSL horizon

    # ONE accounting implementation (telemetry/device.py): the program
    # ledger applies the scan-body-once rule with the learner's DECLARED
    # dispatch multiplier K — the hand-rolled K-correction comment that
    # used to live here is now code. The ledger's `flops` field is the
    # per-iteration body cost; "bytes accessed" counts every logical op's
    # operands/results, so under fusion it OVERSTATES true HBM traffic —
    # the hbm-bound line below is an upper bound on memory time.
    from howtotrainyourmamlpytorch_tpu.telemetry.device import (
        ProgramLedger,
        record_train_program,
    )

    ledger = ProgramLedger(
        peak_flops=V5E_PEAK_F32MULT_FLOPS, emit_events=False
    )
    entry = record_train_program(ledger, learner, state, batches, epoch)
    flops_iter = float(entry.flops or 0.0)
    bytes_iter = float(entry.bytes_accessed or 0.0)
    print(f"flops/iter          : {flops_iter:.3e}")
    print(f"hbm bytes/iter      : {bytes_iter:.3e} (fusion-overcounted upper bound)")
    print(f"dispatch multiplier : K={entry.k} (declared; "
          f"{entry.dispatch_flops or 0.0:.3e} flops/dispatch)")
    if entry.hbm_peak_bytes is not None:
        print(f"hbm peak (static)   : {entry.hbm_peak_bytes:.3e} "
              f"(args {entry.argument_bytes:.3e} + out "
              f"{entry.output_size_bytes:.3e} + temps "
              f"{entry.temp_bytes:.3e})")
    # Bytes-accessed split (operand reads vs output writes) from the same
    # ledger row, so traffic-bound claims — and what each lever
    # (--lane-pad / --compute-dtype / --task-chunk) does to them — are
    # attributable without a profiler trace. Keys are backend-dependent;
    # absent keys print as n/a rather than zero.
    operand_bytes = entry.operand_bytes or 0.0
    output_bytes = entry.output_bytes or 0.0
    if operand_bytes or output_bytes:
        print(f"  operand reads     : {operand_bytes:.3e} "
              f"({100 * operand_bytes / max(bytes_iter, 1.0):.0f}%)")
        print(f"  output writes     : {output_bytes:.3e} "
              f"({100 * output_bytes / max(bytes_iter, 1.0):.0f}%)")
        if flops_iter:
            print(f"  arithmetic int.   : {flops_iter / max(bytes_iter, 1.0):.2f} "
                  "flops/byte (v5e needs ~240 to feed the MXU from HBM)")
    else:
        print("  operand/output split: n/a (backend cost model omits "
              "per-operand byte counts)")

    # Wire bytes per iter (uint8 images + int32 labels).
    xs, xt, ys, yt = learner._prepare_batch(batches[0])
    wire = sum(a.nbytes for a in (xs, xt, ys, yt))
    print(f"wire bytes/iter     : {wire:.3e} (u8) "
          f"/ {4 * (xs.size + xt.size) + ys.nbytes + yt.nbytes:.3e} (f32)")

    # Optional device-side staging (--device-prefetch N): the measured loop
    # and the trace below consume pre-staged device-resident dispatch
    # groups, so a quiet-chip capture shows the staged path — host prep +
    # transfer overlapped with compute — against the unstaged default.
    stager = None
    if args.device_prefetch > 0:
        from howtotrainyourmamlpytorch_tpu.data.device_prefetch import (
            DevicePrefetcher,
        )
        from howtotrainyourmamlpytorch_tpu.models.common import prepare_batch

        def synth_samples():
            while True:
                for b in batches:
                    yield (*b, 0)  # loader sample layout: trailing seed

        stager = DevicePrefetcher(
            synth_samples(),
            lambda b: prepare_batch(b, codec=cfg.wire_codec),
            depth=args.device_prefetch,
            group=K,
        )

    def next_dispatch():
        return next(stager) if stager is not None else batches

    # Measured steady-state rate.
    state, _ = learner.run_train_iters(state, next_dispatch(), epoch=epoch)
    jax.block_until_ready(state.theta)
    if stager is not None:
        # Drop the compile/warm-up waits (the stager filled its whole
        # buffer during the multi-second compile) so the printed split
        # covers only the timed loop.
        stager.pop_waits()
    t0 = time.perf_counter()
    reps = 40
    for _ in range(reps):
        state, _ = learner.run_train_iters(state, next_dispatch(), epoch=epoch)
    jax.block_until_ready(state.theta)
    dt = time.perf_counter() - t0
    per_iter = dt / (reps * K)
    print(f"measured wall/iter  : {per_iter*1e6:.1f} us "
          f"({reps*K/dt:.0f} meta-iters/s)")
    if stager is not None:
        data_wait_s, stage_wait_s = stager.pop_waits()
        print(f"stage-wait split    : data_wait {data_wait_s:.3f}s / "
              f"stage_wait {stage_wait_s:.3f}s over {dt:.3f}s "
              f"(depth {stager.depth})")

    mxu = flops_iter / V5E_PEAK_F32MULT_FLOPS
    hbm = bytes_iter / V5E_HBM_BYTES_PER_S
    print(f"mxu-bound time/iter : {mxu*1e6:.1f} us "
          f"(MFU {100*mxu/per_iter:.1f}% of f32-mult peak)")
    print(f"hbm upper bound/iter: {hbm*1e6:.1f} us "
          "(from fusion-overcounted bytes; not a tight bound)")
    slack = per_iter - mxu
    print(f"non-MXU time/iter   : {slack*1e6:.1f} us "
          "(HBM traffic + non-matmul ops + relayouts + overhead)")

    if args.trace:
        jax.profiler.start_trace(args.trace)
        for _ in range(3):
            state, _ = learner.run_train_iters(
                state, next_dispatch(), epoch=epoch
            )
        jax.block_until_ready(state.theta)
        jax.profiler.stop_trace()
        print(f"trace written to {args.trace}")
    if stager is not None:
        stager.close()


if __name__ == "__main__":
    main()
