"""Numerical parity harness: this framework vs the reference torch code.

Loads the reference implementation from /root/reference (CPU torch), copies
its freshly-initialized weights into our TrainState, feeds BOTH the same
episode batches, and compares per-iteration losses/accuracies and the
evolving parameters. Answers "is our MAML++ step the same function?"
independently of init/hyperparameter choices.

Usage: JAX_PLATFORMS=cpu python tools/parity_check.py --ways 20 --iters 20
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

# APPEND, not prepend: the reference also contains a top-level
# script_generation_tools package; prepending would shadow this repo's
# (it broke tests/test_config_surface.py when collected together).
sys.path.append("/root/reference")

from howtotrainyourmamlpytorch_tpu.utils.platform import (  # noqa: E402
    force_virtual_cpu,
)

# The axon sitecustomize pre-imports jax targeting the TPU; retarget to CPU
# BEFORE any backend initializes so the comparison runs both sides on the
# same host arithmetic (TPU default-precision convs are bf16-multiplied and
# would dominate the diff).
force_virtual_cpu(1)

import torch  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from howtotrainyourmamlpytorch_tpu.models import (  # noqa: E402
    BackboneConfig,
    MAMLConfig,
    MAMLFewShotLearner,
)
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import Bunch  # noqa: E402


def _reference_args(ways, steps, filters, meta_lr, msl_epochs, second_order,
                    **overrides):
    d = dict(
        batch_size=2, image_height=28, image_width=28, image_channels=1,
        num_stages=4, cnn_num_filters=filters, conv_padding=True,
        max_pooling=True, norm_layer="batch_norm",
        per_step_bn_statistics=True,
        number_of_training_steps_per_iter=steps,
        number_of_evaluation_steps_per_iter=steps,
        num_classes_per_set=ways, num_samples_per_class=1,
        num_target_samples=1,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        enable_inner_loop_optimizable_bn_params=False,
        learnable_bn_gamma=True, learnable_bn_beta=True,
        task_learning_rate=0.1, init_inner_loop_learning_rate=0.1,
        second_order=second_order, first_order_to_second_order_epoch=-1,
        use_multi_step_loss_optimization=True,
        multi_step_loss_num_epochs=msl_epochs,
        meta_learning_rate=meta_lr, min_learning_rate=1e-5,
        total_epochs=100, seed=104, use_gdrive=False,
        device=torch.device("cpu"), use_cuda=False, gpu_to_use=0,
        dataset_name="omniglot_dataset", weight_decay=0.0,
    )
    d.update(overrides)
    return Bunch(d)


def copy_torch_backbone(sd, theta):
    """Torch VGGReLUNormNetwork state_dict (already materialized as real
    numpy copies) -> (theta, bn_state) pytrees. The produced arrays take
    the state_dict's shapes, which cover per-step (S, F) and shared (F,)
    BN layouts alike."""
    from howtotrainyourmamlpytorch_tpu.ops.norm import BatchNormState

    theta = jax.tree_util.tree_map(lambda x: x, theta)
    bn = {}
    for i in range(4):
        stage = theta[f"conv{i}"]
        stage["conv"]["weight"] = jnp.asarray(
            sd[f"layer_dict.conv{i}.conv.weight"])
        stage["conv"]["bias"] = jnp.asarray(
            sd[f"layer_dict.conv{i}.conv.bias"])
        stage["norm"]["gamma"] = jnp.asarray(
            sd[f"layer_dict.conv{i}.norm_layer.weight"])
        stage["norm"]["beta"] = jnp.asarray(
            sd[f"layer_dict.conv{i}.norm_layer.bias"])
        bn[f"conv{i}"] = BatchNormState(
            running_mean=jnp.asarray(
                sd[f"layer_dict.conv{i}.norm_layer.running_mean"]),
            running_var=jnp.asarray(
                sd[f"layer_dict.conv{i}.norm_layer.running_var"]),
        )
    theta["linear"]["weight"] = jnp.asarray(sd["layer_dict.linear.weights"])
    theta["linear"]["bias"] = jnp.asarray(sd["layer_dict.linear.bias"])
    return theta, bn


def make_episode_batch(rng, protos, b, n, k, t):
    """(xs, xt, ys, yt) episode batch in the (B, N, S, C, H, W) layout both
    implementations consume; the single source of the test batch shape.
    Image shape is taken from ``protos`` ((N, C, H, W))."""
    c, h, w = protos.shape[1:]
    xs = np.stack([
        protos + 0.3 * rng.randn(n, c, h, w).astype("f")
        for _ in range(b * (k + t))
    ]).reshape(b, k + t, n, c, h, w).transpose(0, 2, 1, 3, 4, 5)
    ys = np.tile(np.arange(n)[None, :, None], (b, 1, k + t))
    return (xs[:, :, :k], xs[:, :, k:],
            ys[:, :, :k].astype(np.int64), ys[:, :, k:].astype(np.int64))


def _build_reference_baseline(cls, ways, steps, filters):
    args = _reference_args(
        ways, steps, filters, 1e-3, 10, False,
        per_step_bn_statistics=False,
        learnable_per_layer_per_step_inner_loop_learning_rate=False,
        use_multi_step_loss_optimization=False,
    )
    return cls(im_shape=(2, 1, 28, 28), device=torch.device("cpu"), args=args)


def build_reference_matching_nets(ways, filters):
    from matching_nets import MatchingNetsFewShotClassifier

    return _build_reference_baseline(MatchingNetsFewShotClassifier, ways, 1,
                                     filters)


def build_reference_gradient_descent(ways, steps, filters):
    from gradient_descent import GradientDescentFewShotClassifier

    return _build_reference_baseline(GradientDescentFewShotClassifier, ways,
                                     steps, filters)


def build_reference(ways, steps, filters, meta_lr, msl_epochs, second_order):
    from few_shot_learning_system import MAMLFewShotClassifier

    args = _reference_args(ways, steps, filters, meta_lr, msl_epochs,
                           second_order)
    return MAMLFewShotClassifier(
        im_shape=(2, 1, 28, 28), device=torch.device("cpu"), args=args
    )


def build_ours(ways, steps, filters, meta_lr, msl_epochs, second_order):
    cfg = MAMLConfig(
        backbone=BackboneConfig(
            num_stages=4, num_filters=filters, per_step_bn_statistics=True,
            num_steps=steps, num_classes=ways, image_channels=1,
            max_pooling=True,
        ),
        number_of_training_steps_per_iter=steps,
        number_of_evaluation_steps_per_iter=steps,
        task_learning_rate=0.1,
        learnable_per_layer_per_step_inner_loop_learning_rate=True,
        second_order=second_order, first_order_to_second_order_epoch=-1,
        use_multi_step_loss_optimization=True,
        multi_step_loss_num_epochs=msl_epochs,
        meta_learning_rate=meta_lr, min_learning_rate=1e-5,
        total_epochs=100,
    )
    learner = MAMLFewShotLearner(cfg)
    return learner, learner.init_state(jax.random.PRNGKey(0))


def copy_torch_params_into_state(ref, state):
    """Overwrites our theta/lslr/bn_state with the torch model's values."""
    # REAL copies: on CPU jax, jnp.asarray of a torch-backed numpy view can
    # be zero-copy, and torch's in-place Adam update would then silently
    # rewrite "our" parameters mid-comparison.
    sd = {k: np.array(v.detach().cpu().numpy(), copy=True)
          for k, v in ref.classifier.state_dict().items()}
    theta, bn = copy_torch_backbone(sd, state.theta)
    # LSLR init is 0.1 on both sides; copy anyway for exactness.
    lrs = {k.replace("names_learning_rates_dict.", ""):
           np.array(v.detach().numpy(), copy=True)
           for k, v in ref.inner_loop_optimizer.named_parameters()}
    lslr = jax.tree_util.tree_map(lambda x: x, state.lslr)
    for i in range(4):
        lslr[f"conv{i}"]["conv"]["weight"] = jnp.asarray(
            lrs[f"layer_dict-conv{i}-conv-weight"])
        lslr[f"conv{i}"]["conv"]["bias"] = jnp.asarray(
            lrs[f"layer_dict-conv{i}-conv-bias"])
    lslr["linear"]["weight"] = jnp.asarray(lrs["layer_dict-linear-weights"])
    lslr["linear"]["bias"] = jnp.asarray(lrs["layer_dict-linear-bias"])
    return state._replace(theta=theta, bn_state=bn, lslr=lslr)


def torch_theta(ref):
    sd = {k: v.detach().cpu().numpy()
          for k, v in ref.classifier.state_dict().items()}
    flat = {}
    for i in range(4):
        flat[f"conv{i}.w"] = sd[f"layer_dict.conv{i}.conv.weight"]
        flat[f"conv{i}.gamma"] = sd[f"layer_dict.conv{i}.norm_layer.weight"]
    flat["linear.w"] = sd["layer_dict.linear.weights"]
    return flat


def our_theta(state):
    t = state.theta
    flat = {}
    for i in range(4):
        flat[f"conv{i}.w"] = np.asarray(t[f"conv{i}"]["conv"]["weight"])
        flat[f"conv{i}.gamma"] = np.asarray(t[f"conv{i}"]["norm"]["gamma"])
    flat["linear.w"] = np.asarray(t["linear"]["weight"])
    return flat


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ways", type=int, default=5)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--steps", type=int, default=3)
    ap.add_argument("--filters", type=int, default=8)
    ap.add_argument("--meta_lr", type=float, default=1e-3)
    ap.add_argument("--msl_epochs", type=int, default=10)
    ap.add_argument("--epoch", type=int, default=0)
    ap.add_argument("--first_order", action="store_true")
    args = ap.parse_args()

    second = not args.first_order
    torch.manual_seed(104)
    ref = build_reference(args.ways, args.steps, args.filters, args.meta_lr,
                          args.msl_epochs, second)
    learner, state = build_ours(args.ways, args.steps, args.filters,
                                args.meta_lr, args.msl_epochs, second)
    state = copy_torch_params_into_state(ref, state)

    b, n, k, t = 2, args.ways, 1, 1
    rng = np.random.RandomState(7)
    protos = rng.randn(n, 1, 28, 28).astype("f")

    def batch():
        return make_episode_batch(rng, protos, b, n, k, t)

    print(f"ways={args.ways} steps={args.steps} filters={args.filters} "
          f"second_order={second} epoch={args.epoch}")
    print(f"{'it':>3} {'ref_loss':>10} {'our_loss':>10} {'dloss':>9} "
          f"{'ref_acc':>8} {'our_acc':>8} {'max|dtheta|':>12}")
    for it in range(args.iters):
        xs, xt, ys, yt = batch()
        # reference per-task shapes: x (n, s, c, h, w), y (n, s)
        tb = (torch.tensor(xs), torch.tensor(xt),
              torch.tensor(ys), torch.tensor(yt))
        ref_losses, _ = ref.run_train_iter(data_batch=tb, epoch=args.epoch)
        state, our_losses = learner.run_train_iter(
            state, (xs, xt, ys, yt), args.epoch)
        rt, ot = torch_theta(ref), our_theta(state)
        dmax = max(np.max(np.abs(rt[key] - ot[key])) for key in rt)
        rl = float(ref_losses["loss"]); ol = float(our_losses["loss"])
        print(f"{it:>3} {rl:>10.6f} {ol:>10.6f} {abs(rl-ol):>9.2e} "
              f"{float(ref_losses['accuracy']):>8.4f} "
              f"{float(our_losses['accuracy']):>8.4f} {dmax:>12.3e}")


if __name__ == "__main__":
    main()


