"""Few-shot adaptation serving CLI: checkpoint in, HTTP endpoint out.

Boots the ``howtotrainyourmamlpytorch_tpu/serve`` runtime against a trained
experiment: the model/architecture comes from the SAME experiment config
JSON the training run used (so serving can never silently disagree with
training about the network), the weights from a manifest-verified
checkpoint loaded params+BN-only (``utils/checkpoint.load_for_inference`` —
no optimizer moments in serving RAM).

Usage::

    python tools/serve_maml.py \
        --config experiment_config/omniglot_maml++_omniglot_5_8_1_48_5_1.json \
        --checkpoint <experiment>/saved_models/train_model_latest \
        [--learner maml|gradient_descent|matching_nets] \
        [--host 127.0.0.1] [--port 8080] [--port_file /run/serve.port] \
        [--max_batch 4] [--max_wait_ms 2.0] [--cache_capacity 256] \
        [--max_queue_depth 64] [--degrade_queue_depth 16] \
        [--warmup 5x1x15,5x5x15] [--init_from_scratch] \
        [--replicas 2] [--telemetry logs/serve_telemetry.jsonl]

Then::

    curl localhost:8080/healthz
    curl -d @episode.json localhost:8080/v1/episode
    curl -d '{"checkpoint": "<path>"}' localhost:8080/admin/promote
    curl localhost:8080/metrics

``--replicas N`` runs the resilience topology: N worker processes (this
same CLI, one engine each, crash-isolated) supervised by a
``serve/pool.ReplicaPool`` — health-checked, restarted with backoff and a
crash-loop circuit breaker — behind one front door. ``--port 0`` binds an
ephemeral port; ``--port_file`` announces whichever port was bound (how
pool workers report back).

``--init_from_scratch`` serves freshly initialized weights (smoke tests,
latency rehearsal on a cold box) instead of requiring a checkpoint.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import tempfile
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LEARNERS = ("maml", "gradient_descent", "matching_nets")


def parse_warmup(spec: str) -> list[tuple[int, int, int]]:
    """``"5x1x15,20x1x5"`` -> ``[(5, 1, 15), (20, 1, 5)]``."""
    buckets = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        dims = part.split("x")
        if len(dims) != 3:
            raise ValueError(
                f"warmup bucket {part!r} must be WAYxSHOTxQUERY (e.g. 5x1x15)"
            )
        buckets.append(tuple(int(d) for d in dims))
    return buckets


def build_learner(name: str, config_path: str):
    """Learner from an experiment config JSON, via the training-run path
    (``get_args`` JSON merge -> ``args_to_maml_config``)."""
    from howtotrainyourmamlpytorch_tpu.models import (
        GradientDescentLearner,
        MAMLFewShotLearner,
        MatchingNetsLearner,
    )
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        args_to_maml_config,
        get_args,
    )

    os.environ.setdefault("DATASET_DIR", "datasets")  # serving reads no data
    args, _ = get_args(["--name_of_args_json_file", config_path])
    cfg = args_to_maml_config(args)
    cls = {
        "maml": MAMLFewShotLearner,
        "gradient_descent": GradientDescentLearner,
        "matching_nets": MatchingNetsLearner,
    }[name]
    return cls(cfg)


def build_pool(opts):
    """The ``--replicas N`` topology: N worker subprocesses (this CLI, one
    engine each) under pool supervision."""
    from howtotrainyourmamlpytorch_tpu.serve.pool import (
        PoolConfig,
        ReplicaPool,
    )
    from howtotrainyourmamlpytorch_tpu.serve.resilience.replica import (
        SubprocessReplica,
        serve_maml_argv,
    )

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    run_dir = tempfile.mkdtemp(prefix="serve_pool_")

    def factory(index: int) -> SubprocessReplica:
        port_file = os.path.join(run_dir, f"replica_{index}.port")
        try:
            os.remove(port_file)
        except OSError:
            pass
        argv = serve_maml_argv(
            opts.config,
            port_file=port_file,
            checkpoint=opts.checkpoint,
            learner=opts.learner,
            warmup=opts.warmup,
            telemetry=opts.telemetry,
            max_batch=opts.max_batch,
            max_wait_ms=opts.max_wait_ms,
            cache_capacity=opts.cache_capacity,
            max_queue_depth=opts.max_queue_depth,
            degrade_queue_depth=opts.degrade_queue_depth,
            max_queue_age_ms=opts.max_queue_age_ms,
            retry_after_s=opts.retry_after_s,
            repo_root=repo_root,
        )
        return SubprocessReplica(
            argv, replica_id=f"worker-{index}", port_file=port_file
        )

    return ReplicaPool(
        factory,
        PoolConfig(
            n_replicas=opts.replicas,
            health_interval_s=opts.health_interval_s,
            restart_backoff_s=opts.restart_backoff_s,
        ),
    )


def main(argv=None) -> int:
    # Multi-host bring-up BEFORE any device probe (the same ordering the
    # training entry points follow, enforced by graftlint's
    # device-probe-before-distributed-init): a no-op without an explicit
    # env signal, and fail-fast with a typed DistributedInitError when a
    # configured coordinator is unreachable.
    from howtotrainyourmamlpytorch_tpu.parallel import (
        initialize_distributed_from_argv,
    )

    initialize_distributed_from_argv([])
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--config", required=True,
                        help="experiment config JSON (the training run's)")
    parser.add_argument("--checkpoint", default=None,
                        help="checkpoint file (e.g. .../train_model_latest)")
    parser.add_argument("--learner", choices=LEARNERS, default="maml")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--port_file", default=None,
                        help="write the bound port here once listening "
                        "(pool workers announce their ephemeral port)")
    parser.add_argument("--max_batch", type=int, default=4)
    parser.add_argument("--max_wait_ms", type=float, default=2.0)
    parser.add_argument("--cache_capacity", type=int, default=256)
    parser.add_argument("--max_queue_depth", type=int, default=64,
                        help="admission hard limit: shed (503 + Retry-After)"
                        " at this queue depth")
    parser.add_argument("--degrade_queue_depth", type=int, default=16,
                        help="admission soft limit: shed cold-adapt traffic "
                        "past this depth, keep cache hits (0 disables)")
    parser.add_argument("--max_queue_age_ms", type=float, default=2000.0)
    parser.add_argument("--retry_after_s", type=float, default=1.0)
    parser.add_argument("--warmup", default="",
                        help="comma-separated WAYxSHOTxQUERY buckets to "
                        "pre-compile before accepting traffic")
    parser.add_argument("--telemetry", default=None,
                        help="append serve telemetry events "
                        "(serve_dispatch with per-episode margin/entropy/"
                        "tags, serve_compile, swap/promotion events) to "
                        "this JSONL; --replicas workers share the path "
                        "(concurrent appends are reader-tolerated) — the "
                        "feed tools/episode_miner.py mines")
    parser.add_argument("--init_from_scratch", action="store_true",
                        help="serve fresh init weights (no checkpoint)")
    parser.add_argument("--replicas", type=int, default=0,
                        help="run N supervised worker subprocesses behind "
                        "this front door (0 = single-process)")
    parser.add_argument("--health_interval_s", type=float, default=0.5)
    parser.add_argument("--restart_backoff_s", type=float, default=1.0)
    opts = parser.parse_args(argv)
    telemetry_stop = None
    telemetry_flusher = None
    telemetry_sink = None
    if opts.telemetry:
        # Engines emit host-buffered events (serve/engine.py); a serving
        # process has no trainer forced-read boundary to flush at, so a
        # small cadence thread drains the buffer instead (joined on every
        # exit path below — thread-lifecycle).
        from howtotrainyourmamlpytorch_tpu.telemetry import (
            events as tel_events,
        )
        from howtotrainyourmamlpytorch_tpu.telemetry.events import EventLog

        parent = os.path.dirname(os.path.abspath(opts.telemetry))
        os.makedirs(parent, exist_ok=True)
        telemetry_sink = EventLog(opts.telemetry)
        tel_events.install(telemetry_sink)
        tel_events.ensure_trace_id()
        telemetry_stop = threading.Event()

        def _flush_loop():
            while not telemetry_stop.is_set():
                telemetry_sink.flush()
                telemetry_stop.wait(1.0)
            telemetry_sink.flush()

        telemetry_flusher = threading.Thread(
            target=_flush_loop, name="serve-telemetry-flusher", daemon=True
        )
        telemetry_flusher.start()
    if not opts.checkpoint and not opts.init_from_scratch:
        parser.error("--checkpoint is required (or pass --init_from_scratch)")
    if opts.replicas > 0 and not opts.warmup:
        # Readiness is warmup-gated: a worker that never warms answers 503
        # on /healthz forever, the supervisor keeps it in STARTING, and the
        # pool would deadlock with zero routable replicas. Require the
        # operator to declare the serving buckets up front.
        parser.error(
            "--replicas requires --warmup WAYxSHOTxQUERY[,...]: pool "
            "workers only become routable after warming their buckets"
        )

    from howtotrainyourmamlpytorch_tpu.serve import make_http_server

    if opts.replicas > 0:
        target = build_pool(opts)
        detail = f"{opts.replicas}-replica pool"
    else:
        import jax

        from howtotrainyourmamlpytorch_tpu.serve import (
            ServeConfig,
            ServingAPI,
        )

        learner = build_learner(opts.learner, opts.config)
        if opts.init_from_scratch:
            state, exp_state = (
                learner.init_inference_state(jax.random.PRNGKey(0)), {}
            )
        else:
            # Learner-aware load: params+BN prefix, manifest-verified, plus
            # any serve-time state derived from the checkpoint's recorded
            # progress (GD recomputes its epoch-schedule fine-tune lr).
            state, exp_state = learner.load_inference_state(opts.checkpoint)
        target = ServingAPI(
            learner,
            state,
            ServeConfig(
                meta_batch_size=opts.max_batch,
                max_wait_ms=opts.max_wait_ms,
                cache_capacity=opts.cache_capacity,
                max_queue_depth=opts.max_queue_depth,
                degrade_queue_depth=opts.degrade_queue_depth,
                max_queue_age_ms=opts.max_queue_age_ms,
                retry_after_s=opts.retry_after_s,
            ),
        )
        if opts.warmup:
            buckets = parse_warmup(opts.warmup)
            print(f"warming {len(buckets)} bucket(s): {buckets}", flush=True)
            target.engine.warmup(buckets)
        detail = (
            f"{opts.learner} "
            f"(epoch state: {exp_state.get('current_iter', 'fresh')})"
        )

    try:
        server = make_http_server(target, opts.host, opts.port)
    except Exception:
        # Bind failure (EADDRINUSE, bad host) after build_pool has already
        # spawned worker subprocesses: reap them instead of orphaning N
        # live engines under init.
        target.close()
        raise
    host, port = server.server_address[:2]
    if opts.port_file:
        tmp = opts.port_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(str(port))
        os.replace(tmp, opts.port_file)  # atomic: readers never see partial
    print(
        f"serving {detail} on http://{host}:{port} — "
        "/v1/episode /admin/promote /healthz /metrics",
        flush=True,
    )

    # SIGTERM must drain through the finally block: in pool mode the worker
    # SUBPROCESSES are children of this front door, and dying without
    # pool.close() would orphan N live engines (observed: kill -TERM left
    # every worker running under init). shutdown() is called off-thread —
    # calling it from the handler inside serve_forever would deadlock.
    def _graceful_exit(signum, frame):
        threading.Thread(target=server.shutdown, daemon=True).start()

    previous_sigterm = signal.signal(signal.SIGTERM, _graceful_exit)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        signal.signal(signal.SIGTERM, previous_sigterm)
        server.server_close()
        target.close()
        if telemetry_stop is not None:
            telemetry_stop.set()
            telemetry_flusher.join(timeout=10)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
