"""Few-shot adaptation serving CLI: checkpoint in, HTTP endpoint out.

Boots the ``howtotrainyourmamlpytorch_tpu/serve`` runtime against a trained
experiment: the model/architecture comes from the SAME experiment config
JSON the training run used (so serving can never silently disagree with
training about the network), the weights from a manifest-verified
checkpoint loaded params+BN-only (``utils/checkpoint.load_for_inference`` —
no optimizer moments in serving RAM).

Usage::

    python tools/serve_maml.py \
        --config experiment_config/omniglot_maml++_omniglot_5_8_1_48_5_1.json \
        --checkpoint <experiment>/saved_models/train_model_latest \
        [--learner maml|gradient_descent|matching_nets] \
        [--host 127.0.0.1] [--port 8080] \
        [--max_batch 4] [--max_wait_ms 2.0] [--cache_capacity 256] \
        [--warmup 5x1x15,5x5x15] [--init_from_scratch]

Then::

    curl localhost:8080/healthz
    curl -d @episode.json localhost:8080/v1/episode
    curl localhost:8080/metrics

``--init_from_scratch`` serves freshly initialized weights (smoke tests,
latency rehearsal on a cold box) instead of requiring a checkpoint.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

LEARNERS = ("maml", "gradient_descent", "matching_nets")


def parse_warmup(spec: str) -> list[tuple[int, int, int]]:
    """``"5x1x15,20x1x5"`` -> ``[(5, 1, 15), (20, 1, 5)]``."""
    buckets = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        dims = part.split("x")
        if len(dims) != 3:
            raise ValueError(
                f"warmup bucket {part!r} must be WAYxSHOTxQUERY (e.g. 5x1x15)"
            )
        buckets.append(tuple(int(d) for d in dims))
    return buckets


def build_learner(name: str, config_path: str):
    """Learner from an experiment config JSON, via the training-run path
    (``get_args`` JSON merge -> ``args_to_maml_config``)."""
    from howtotrainyourmamlpytorch_tpu.models import (
        GradientDescentLearner,
        MAMLFewShotLearner,
        MatchingNetsLearner,
    )
    from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
        args_to_maml_config,
        get_args,
    )

    os.environ.setdefault("DATASET_DIR", "datasets")  # serving reads no data
    args, _ = get_args(["--name_of_args_json_file", config_path])
    cfg = args_to_maml_config(args)
    cls = {
        "maml": MAMLFewShotLearner,
        "gradient_descent": GradientDescentLearner,
        "matching_nets": MatchingNetsLearner,
    }[name]
    return cls(cfg)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--config", required=True,
                        help="experiment config JSON (the training run's)")
    parser.add_argument("--checkpoint", default=None,
                        help="checkpoint file (e.g. .../train_model_latest)")
    parser.add_argument("--learner", choices=LEARNERS, default="maml")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=8080)
    parser.add_argument("--max_batch", type=int, default=4)
    parser.add_argument("--max_wait_ms", type=float, default=2.0)
    parser.add_argument("--cache_capacity", type=int, default=256)
    parser.add_argument("--warmup", default="",
                        help="comma-separated WAYxSHOTxQUERY buckets to "
                        "pre-compile before accepting traffic")
    parser.add_argument("--init_from_scratch", action="store_true",
                        help="serve fresh init weights (no checkpoint)")
    opts = parser.parse_args(argv)
    if not opts.checkpoint and not opts.init_from_scratch:
        parser.error("--checkpoint is required (or pass --init_from_scratch)")

    import jax

    from howtotrainyourmamlpytorch_tpu.serve import (
        ServeConfig,
        ServingAPI,
        make_http_server,
    )

    learner = build_learner(opts.learner, opts.config)
    if opts.init_from_scratch:
        state, exp_state = (
            learner.init_inference_state(jax.random.PRNGKey(0)), {}
        )
    else:
        # Learner-aware load: params+BN prefix, manifest-verified, plus any
        # serve-time state derived from the checkpoint's recorded progress
        # (GD recomputes its epoch-schedule fine-tune lr here).
        state, exp_state = learner.load_inference_state(opts.checkpoint)
    api = ServingAPI(
        learner,
        state,
        ServeConfig(
            meta_batch_size=opts.max_batch,
            max_wait_ms=opts.max_wait_ms,
            cache_capacity=opts.cache_capacity,
        ),
    )
    if opts.warmup:
        buckets = parse_warmup(opts.warmup)
        print(f"warming {len(buckets)} bucket(s): {buckets}", flush=True)
        api.engine.warmup(buckets)

    server = make_http_server(api, opts.host, opts.port)
    host, port = server.server_address[:2]
    print(
        f"serving {opts.learner} "
        f"(epoch state: {exp_state.get('current_iter', 'fresh')}) "
        f"on http://{host}:{port} — /v1/episode /healthz /metrics",
        flush=True,
    )
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        server.server_close()
        api.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
