"""Open-loop serving load test: Poisson arrivals, SLO verdict, recovery.

The serving ROADMAP item asks for "heavy traffic from millions of users"
as a MEASURED claim, and the resilience layer's whole value — bounded
queues, 503-not-meltdown overload behavior, replica recovery — only shows
up under an arrival process that does not politely wait for responses.
This harness offers exactly that:

* **open-loop arrivals** — request start times are drawn ONCE from a
  seeded Poisson process (exponential inter-arrivals at ``--rate``) and
  fired on schedule regardless of completions, so an overloaded server
  faces mounting concurrency exactly like production traffic (a
  closed-loop bench self-throttles and hides overload entirely);
* **ramp schedules** — ``--ramp lo:hi:dur[,...]`` replaces the flat rate
  with a piecewise-triangle arrival intensity (``2:20:60`` climbs 2→20
  qps over 60 s then back down over another 60 s), realized as a seeded
  NONhomogeneous Poisson process via thinning — the traffic shape an
  autoscaler must follow; ``serve_ramp_p99_ms`` reports the p99 over the
  whole swing;
* **SLO verdict** — ``p99 <= --p99-budget-ms`` AND ``error rate <=
  --error-slo`` over the run, printed as a machine-readable JSON line with
  ``--json`` (exit code 0 pass / 2 fail, so CI can gate on it);
* **recovery measurement** — a health sampler tracks degraded windows
  (pool: healthy replicas below size; single engine: ``ready`` false), and
  ``serve_recovery_s`` reports the longest one — with
  ``--kill-replica-at K`` it is the measured replica-death-to-full-health
  time under live traffic;
* **durable tier** — ``--tier-dir DIR`` gives every replica a
  crash-consistent tier at ``DIR/replica-<i>`` (artifact spill + AOT
  executable cache) and digest-affine ring routing; a killed replica then
  respawns WARM (rehydrate, not recompile) and ``serve_replica_ready_s``
  reports the measured factory-to-HEALTHY time of the newest respawn.

Targets: in-process single engine (default; ``--tiny`` for the CI-sized
model), in-process supervised replica pool (``--replicas N``), or any
running server (``--url http://host:port``).

Keys (``serve_slo_p99_ms``, ``serve_error_rate``, ``serve_recovery_s``)
also flow into ``tools/serve_bench.py`` output so the bench can never
report healthy-looking qps while silently shedding.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

OUTCOME_OK = "ok"
OUTCOME_SHED = "shed"
OUTCOME_DEADLINE = "deadline"
OUTCOME_ERROR = "error"


# ---------------------------------------------------------------------------
# Ramp schedules (nonhomogeneous Poisson arrivals)
# ---------------------------------------------------------------------------


def parse_ramp(spec: str) -> list[tuple[float, float, float]]:
    """``lo:hi:dur[,lo:hi:dur...]`` → validated ``(lo, hi, dur)`` segments.

    Each segment is a TRIANGLE: the rate climbs lo→hi over ``dur``
    seconds, then descends hi→lo over another ``dur`` seconds, so one
    segment occupies ``2*dur`` of wall clock. Segments concatenate."""
    segments = []
    for part in spec.split(","):
        fields = part.split(":")
        if len(fields) != 3:
            raise ValueError(
                f"ramp segment {part!r} must be lo:hi:dur (e.g. 2:20:60)"
            )
        lo, hi, dur = (float(f) for f in fields)
        if lo < 0 or hi <= 0 or dur <= 0:
            raise ValueError(
                f"ramp segment {part!r}: need lo >= 0, hi > 0, dur > 0"
            )
        if hi < lo:
            raise ValueError(
                f"ramp segment {part!r}: hi must be >= lo (the segment "
                "ramps up then back down on its own)"
            )
        segments.append((lo, hi, dur))
    return segments


def ramp_rate_fn(segments):
    """``(rate(t), total_duration_s, peak_rate)`` for triangle segments."""
    total = sum(2.0 * dur for _, _, dur in segments)
    peak = max(hi for _, hi, _ in segments)

    def rate(t: float) -> float:
        if t < 0 or t >= total:
            return 0.0
        for lo, hi, dur in segments:
            if t < 2.0 * dur:
                if t < dur:  # climbing
                    return lo + (hi - lo) * (t / dur)
                return hi - (hi - lo) * ((t - dur) / dur)  # descending
            t -= 2.0 * dur
        return 0.0

    return rate, total, peak


def ramp_arrivals(segments, *, seed: int = 0) -> list[float]:
    """Seeded arrival times for the triangle schedule, by thinning.

    Draw a homogeneous Poisson process at the PEAK rate over the whole
    schedule, then keep each arrival ``t`` with probability
    ``rate(t)/peak`` — the standard exact construction for a
    nonhomogeneous Poisson process, so the offered stream is genuinely
    Poisson at every instant of the ramp (bursty where it should be),
    not a deterministic staircase."""
    rate, total, peak = ramp_rate_fn(segments)
    rng = np.random.RandomState(seed)
    arrivals = []
    t = 0.0
    while True:
        t += float(rng.exponential(1.0 / peak))
        if t >= total:
            break
        if rng.rand() <= rate(t) / peak:
            arrivals.append(t)
    return arrivals


class _HealthSampler:
    """Samples the target's health on a cadence and reports the longest
    window in which it was degraded (not all replicas healthy / engine not
    ready) — the recovery clock for replica-death experiments."""

    def __init__(self, target, interval_s: float = 0.05):
        self.target = target
        self.interval_s = interval_s
        self._samples: list[tuple[float, bool]] = []  # (t, fully_healthy)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="loadtest-health", daemon=True
        )

    def _healthy(self) -> bool:
        try:
            try:
                h = self.target.healthz(timeout=2.0)
            except TypeError:  # in-process targets take no timeout kwarg
                h = self.target.healthz()
        except Exception:
            return False
        if "healthy_replicas" in h:
            return h["healthy_replicas"] >= h.get("pool_size", 1)
        return bool(h.get("ready", True)) and not h.get("degraded", False)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._samples.append((time.monotonic(), self._healthy()))
            self._stop.wait(self.interval_s)

    def __enter__(self):
        self._thread.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._thread.join(timeout=5)

    def longest_degraded_window_s(self) -> float:
        worst = 0.0
        window_start: float | None = None
        for t, healthy in self._samples:
            if not healthy and window_start is None:
                window_start = t
            elif healthy and window_start is not None:
                worst = max(worst, t - window_start)
                window_start = None
        if window_start is not None and self._samples:
            worst = max(worst, self._samples[-1][0] - window_start)
        return worst


def synth_episodes(
    n: int, *, way: int, shot: int, query: int, image_shape, seed: int = 0
):
    """``n`` distinct synthetic episodes at one bucket."""
    rng = np.random.RandomState(seed)
    episodes = []
    for _ in range(n):
        xs = rng.rand(way * shot, *image_shape).astype(np.float32)
        ys = np.repeat(np.arange(way), shot).astype(np.int32)
        xq = rng.rand(query, *image_shape).astype(np.float32)
        episodes.append((xs, ys, xq))
    return episodes


def _classify_outcome(
    target, episode, timeout_s: float, tag: str | None = None
) -> str:
    from howtotrainyourmamlpytorch_tpu.serve.errors import OverloadedError

    xs, ys, xq = episode
    try:
        if tag is not None:
            target.classify(xs, ys, xq, timeout=timeout_s, tag=tag)
        else:
            target.classify(xs, ys, xq, timeout=timeout_s)
        return OUTCOME_OK
    except OverloadedError:
        return OUTCOME_SHED
    except TimeoutError:
        return OUTCOME_DEADLINE
    except Exception:
        return OUTCOME_ERROR


def run_loadtest(
    target,
    episodes,
    *,
    rate_qps: float,
    duration_s: float,
    p99_budget_ms: float,
    error_slo: float,
    timeout_s: float = 10.0,
    seed: int = 0,
    max_workers: int = 32,
    sample_health: bool = True,
    tag_seed_base: int | None = None,
    arrivals: list[float] | None = None,
    ramp: str | None = None,
) -> dict:
    """Offers an open-loop Poisson stream to ``target.classify`` and
    returns the measured result + SLO verdict (see module docstring).

    ``target`` is anything with the ``ServingAPI`` classify/healthz
    surface (a pool, or an ``HttpReplica`` pointed at a live server).
    ``episodes`` are cycled round-robin, so distinct support sets keep the
    adapt path honest (pass one episode to measure the pure cache-hit
    tier). ``tag_seed_base`` stamps episode ``i`` with the telemetry tag
    ``seed:<base+i>`` — the replayable identity ``tools/episode_miner.py``
    mines hard episodes by (use the dataset seeds your episodes were
    actually synthesized from when you have them).

    ``arrivals`` overrides the flat-rate Poisson draw with a precomputed
    schedule (e.g. ``ramp_arrivals``); ``ramp`` labels the result and
    turns on the ``serve_ramp_p99_ms`` export."""
    if arrivals is None:
        rng = np.random.RandomState(seed)
        # The whole arrival schedule up front: reproducible, and the
        # firing loop does no RNG work.
        arrivals = []
        t = 0.0
        while t < duration_s:
            t += float(rng.exponential(1.0 / rate_qps))
            if t < duration_s:
                arrivals.append(t)
    results: list[tuple[str, float]] = []
    results_lock = threading.Lock()
    t_start = time.monotonic()

    def fire(index: int, due: float) -> None:
        slot = index % len(episodes)
        tag = (
            f"seed:{tag_seed_base + slot}"
            if tag_seed_base is not None else None
        )
        outcome = _classify_outcome(
            target, episodes[slot], timeout_s, tag=tag
        )
        # Latency is measured from the SCHEDULED arrival, not from when an
        # executor worker got around to the task — client-side queueing
        # under overload is exactly the delay an open-loop harness exists
        # to expose, and timing from dequeue would hide it from the p99.
        latency_ms = (time.monotonic() - (t_start + due)) * 1e3
        with results_lock:
            results.append((outcome, latency_ms))

    sampler = (
        _HealthSampler(target)
        if sample_health and hasattr(target, "healthz")
        else None
    )
    if sampler is not None:
        sampler.__enter__()
    try:
        with ThreadPoolExecutor(max_workers=max_workers) as pool:
            for index, due in enumerate(arrivals):
                delay = (t_start + due) - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                # Open loop: fire on schedule no matter what's in flight;
                # executor exit waits for stragglers.
                pool.submit(fire, index, due)
    finally:
        if sampler is not None:
            sampler.__exit__()
    wall_s = time.monotonic() - t_start
    recovery_s = (
        round(sampler.longest_degraded_window_s(), 3)
        if sampler is not None
        else None
    )

    # Durable-tier receipt: a pool target reports how long its most
    # recent replica took from factory start to HEALTHY — with a warm
    # tier (--tier-dir) this is the rehydrate-not-recompile respawn time.
    replica_ready_s = None
    stats_fn = getattr(target, "stats", None)
    if callable(stats_fn):
        try:
            replica_ready_s = stats_fn().get("replica_ready_s")
        except Exception:
            replica_ready_s = None

    offered = len(arrivals)
    by_outcome = {k: 0 for k in (
        OUTCOME_OK, OUTCOME_SHED, OUTCOME_DEADLINE, OUTCOME_ERROR,
    )}
    ok_latencies = []
    for outcome, latency_ms in results:
        by_outcome[outcome] += 1
        if outcome == OUTCOME_OK:
            ok_latencies.append(latency_ms)
    ok = by_outcome[OUTCOME_OK]
    failed = offered - ok
    error_rate = failed / offered if offered else 0.0
    p50 = float(np.percentile(ok_latencies, 50)) if ok_latencies else 0.0
    p99 = float(np.percentile(ok_latencies, 99)) if ok_latencies else 0.0
    slo_pass = bool(p99 <= p99_budget_ms and error_rate <= error_slo)
    result = {
        "offered": offered,
        "completed_ok": ok,
        "shed": by_outcome[OUTCOME_SHED],
        "deadline_exceeded": by_outcome[OUTCOME_DEADLINE],
        "errors": by_outcome[OUTCOME_ERROR],
        "rate_qps_requested": rate_qps,
        "rate_qps_offered": round(offered / wall_s, 3) if wall_s else 0.0,
        "serve_loadtest_qps": round(ok / wall_s, 3) if wall_s else 0.0,
        "serve_loadtest_p50_ms": round(p50, 3),
        "serve_loadtest_p99_ms": round(p99, 3),
        "serve_slo_p99_ms": p99_budget_ms,
        "serve_error_rate": round(error_rate, 6),
        "serve_shed_rate": round(
            by_outcome[OUTCOME_SHED] / offered, 6
        ) if offered else 0.0,
        "serve_error_slo": error_slo,
        "serve_recovery_s": recovery_s,
        "serve_replica_ready_s": (
            round(replica_ready_s, 3) if replica_ready_s is not None else None
        ),
        "slo_pass": slo_pass,
        "duration_s": round(wall_s, 3),
    }
    if ramp is not None:
        result["ramp"] = ramp
        result["serve_ramp_p99_ms"] = round(p99, 3)
    return result


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _build_local_target(opts):
    """In-process target: a single ServingAPI, or a LocalReplica pool.
    Returns ``(target, backbone_config)`` — the backbone supplies the
    episode geometry for the synthetic stream."""
    from howtotrainyourmamlpytorch_tpu.serve.pool import (
        PoolConfig,
        ReplicaPool,
    )
    from howtotrainyourmamlpytorch_tpu.serve.resilience.replica import (
        LocalReplica,
    )
    from tools.serve_bench import build_api, parse_geometries

    tier_dir = getattr(opts, "tier_dir", None)
    # A --geometry-mix stream needs a lattice-bearing engine: explicit
    # --geometry-lattice, or (default) the elementwise max of the mix —
    # one bucket every mixed episode coarsens onto, the maximally
    # heterogeneous-traffic-through-one-program-set configuration.
    lattice = None
    if getattr(opts, "geometry_mix", None):
        mix = parse_geometries(opts.geometry_mix)
        if getattr(opts, "geometry_lattice", None):
            lattice = parse_geometries(opts.geometry_lattice)
        else:
            lattice = [tuple(max(g[i] for g in mix) for i in range(3))]

    def replica_tier(index: int):
        # Per-replica tier layout matches PoolConfig.tier_root: a
        # restarted slot reuses its dir (warm respawn), a retired slot's
        # dir is rehydrated by its ring successor.
        if not tier_dir:
            return None
        return os.path.join(tier_dir, f"replica-{index}")

    def one_api(replica_tier_dir=None):
        api = build_api(
            opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512,
            tier_dir=replica_tier_dir, geometry_lattice=lattice,
        )
        if lattice is not None:
            api.engine.warmup()  # every lattice bucket
        else:
            way = api.engine.learner.cfg.backbone.num_classes
            api.engine.warmup([(way, opts.shot, opts.query)])
        return api

    if opts.replicas > 0:
        # Slot 0's engine doubles as the geometry source (slots start in
        # order at pool construction); restarts build fresh ones.
        prebuilt = [one_api(replica_tier(0))]
        backbone = prebuilt[0].engine.learner.cfg.backbone

        def factory(index: int) -> LocalReplica:
            if index == 0 and prebuilt:
                api = prebuilt.pop()
            else:
                api = one_api(replica_tier(index))
            return LocalReplica(api, replica_id=f"local-{index}")

        pool = ReplicaPool(
            factory,
            PoolConfig(
                n_replicas=opts.replicas,
                health_interval_s=0.1,
                restart_backoff_s=0.1,
                min_uptime_s=0.5,
                tier_root=tier_dir or None,
                route_by_digest=bool(tier_dir),
            ),
        )
        if not pool.wait_ready(timeout=300.0):
            pool.close()
            raise RuntimeError(
                "in-process replica pool never became healthy — cannot "
                "offer load to a dead fleet"
            )
        return pool, backbone
    api = one_api(tier_dir or None)
    return api, api.engine.learner.cfg.backbone


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--rate", type=float, default=4.0,
                        help="offered Poisson arrival rate, requests/s")
    parser.add_argument("--duration-s", type=float, default=5.0)
    parser.add_argument("--ramp", default=None, metavar="LO:HI:DUR[,...]",
                        help="piecewise-triangle arrival schedule instead "
                        "of the flat --rate: each segment climbs LO→HI "
                        "qps over DUR seconds then back down over another "
                        "DUR (so '2:20:60' is a 10x swing over 120 s); "
                        "overrides --rate/--duration-s and exports "
                        "serve_ramp_p99_ms")
    parser.add_argument("--p99-budget-ms", type=float, default=2000.0)
    parser.add_argument("--error-slo", type=float, default=0.01,
                        help="max tolerated non-OK fraction")
    parser.add_argument("--timeout-s", type=float, default=10.0,
                        help="per-request deadline budget")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--episodes", type=int, default=32,
                        help="distinct support sets cycled by the stream")
    parser.add_argument("--shot", type=int, default=1)
    parser.add_argument("--query", type=int, default=15)
    parser.add_argument("--geometry-mix", default=None,
                        help="comma-separated WxSxQ triples: the stream "
                        "cycles these geometries (seeded "
                        "data.geometry_mix_episodes episodes) instead of "
                        "one fixed bucket; in-process targets get a "
                        "geometry-lattice engine")
    parser.add_argument("--geometry-lattice", default=None,
                        help="declared WxSxQ bucket lattice for "
                        "--geometry-mix in-process targets (default: the "
                        "elementwise max of the mix, a single bucket)")
    parser.add_argument("--tiny", action="store_true",
                        help="CI-sized model for the in-process target")
    parser.add_argument("--max-batch", type=int, default=4)
    parser.add_argument("--replicas", type=int, default=0,
                        help="run against an in-process LocalReplica pool")
    parser.add_argument("--url", default=None,
                        help="load-test a running server instead of an "
                        "in-process target")
    parser.add_argument("--way", type=int, default=5,
                        help="episode way for --url targets (in-process "
                        "targets derive it from the model)")
    parser.add_argument("--image-shape", default="1x28x28",
                        help="CxHxW image geometry for --url targets "
                        "(must match the served model)")
    parser.add_argument("--tag-seed-base", type=int, default=None,
                        help="stamp episode i with telemetry tag "
                        "'seed:<base+i>' (the episode_miner identity)")
    parser.add_argument("--kill-replica-at", type=int, default=None,
                        help="inject replica death at the Kth request "
                        "(in-process targets) and measure recovery")
    parser.add_argument("--tier-dir", default=None,
                        help="durable-tier root for in-process targets: "
                        "replica i spills to <dir>/replica-<i>, the pool "
                        "routes by episode digest, and a killed replica "
                        "respawns warm from its tier")
    parser.add_argument("--json", action="store_true",
                        help="print the result as one JSON line")
    opts = parser.parse_args(argv)

    from howtotrainyourmamlpytorch_tpu.utils import faultinject

    close_target = None
    if opts.url:
        from howtotrainyourmamlpytorch_tpu.serve.resilience.replica import (
            HttpReplica,
        )

        target = HttpReplica(opts.url, replica_id="loadtest")
        # Remote targets can't be introspected: geometry comes from flags.
        dims = tuple(int(d) for d in opts.image_shape.split("x"))
        if len(dims) != 3:
            parser.error("--image-shape must be CxHxW (e.g. 1x28x28)")
        image_shape, way = dims, opts.way
    else:
        target, bb = _build_local_target(opts)
        close_target = target
        image_shape = (bb.image_channels, bb.image_height, bb.image_width)
        way = bb.num_classes

    if opts.geometry_mix:
        from howtotrainyourmamlpytorch_tpu.data import geometry_mix_episodes
        from tools.serve_bench import parse_geometries

        episodes = geometry_mix_episodes(
            opts.episodes, parse_geometries(opts.geometry_mix),
            image_shape=image_shape, seed=opts.seed,
        )
    else:
        episodes = synth_episodes(
            opts.episodes, way=way, shot=opts.shot, query=opts.query,
            image_shape=image_shape, seed=opts.seed,
        )
    if opts.kill_replica_at is not None:
        faultinject.activate(
            faultinject.FaultPlan(
                replica_kill_at_request=opts.kill_replica_at
            )
        )
    rate_qps, duration_s, arrivals = opts.rate, opts.duration_s, None
    if opts.ramp:
        try:
            segments = parse_ramp(opts.ramp)
        except ValueError as exc:
            parser.error(str(exc))
        arrivals = ramp_arrivals(segments, seed=opts.seed)
        _, duration_s, rate_qps = ramp_rate_fn(segments)
    try:
        result = run_loadtest(
            target,
            episodes,
            rate_qps=rate_qps,
            duration_s=duration_s,
            p99_budget_ms=opts.p99_budget_ms,
            error_slo=opts.error_slo,
            timeout_s=opts.timeout_s,
            seed=opts.seed,
            tag_seed_base=opts.tag_seed_base,
            arrivals=arrivals,
            ramp=opts.ramp or None,
        )
    finally:
        if opts.kill_replica_at is not None:
            faultinject.deactivate()
        if close_target is not None:
            close_target.close()
    result["target"] = opts.url or (
        f"in-process pool x{opts.replicas}" if opts.replicas
        else "in-process"
    )
    if opts.json:
        print(json.dumps(result))
    else:
        verdict = "PASS" if result["slo_pass"] else "FAIL"
        print(
            f"[{verdict}] offered {result['offered']} @ "
            f"{result['rate_qps_requested']} qps for "
            f"{result['duration_s']} s: ok {result['completed_ok']}, "
            f"shed {result['shed']}, deadline {result['deadline_exceeded']},"
            f" errors {result['errors']}; p99 "
            f"{result['serve_loadtest_p99_ms']} ms (budget "
            f"{result['serve_slo_p99_ms']}), error rate "
            f"{result['serve_error_rate']} (slo {result['serve_error_slo']})"
            f", recovery {result['serve_recovery_s']} s, replica ready "
            f"{result['serve_replica_ready_s']} s"
        )
    return 0 if result["slo_pass"] else 2


if __name__ == "__main__":
    raise SystemExit(main())
