"""Promotion daemon CLI: the continuous train→serve control loop.

Watches a trainer's checkpoint directory (``saved_models/``) for fully-
published epoch checkpoints (``.ready`` done-markers), stages + verifies +
val-gates each candidate, drives the fleet's canary-first
``/admin/promote`` with retry/backoff, journals every phase to a
crash-safe ``logs/promotions.jsonl`` (SIGKILL at any boundary, restart,
and the run resumes idempotently — no double-promote, no skipped
candidate), and after every publish watches the front door's ``/metrics``
for live regression — rolling back to the retained last-known-good
checkpoint automatically.

Usage::

    python tools/promotion_daemon.py \
        --watch <experiment>/saved_models \
        --target http://127.0.0.1:8080 \
        [--journal <experiment>/logs/promotions.jsonl] \
        [--staging <experiment>/promotion_staging] \
        [--telemetry <experiment>/logs/telemetry.jsonl] \
        [--poll_interval_s 2.0] [--val_stat val_accuracy_mean] \
        [--val_min_delta 0.0] [--allow_missing_val_stat] \
        [--slo_watch_s 10] [--slo_poll_s 0.5] \
        [--p99_budget_ms 30000] [--max_error_rate 0.05] \
        [--max_new_nonfinite 0] [--min_requests 1] \
        [--promote_retries 3] [--promote_backoff_s 0.5] \
        [--max_promotions 0] [--once]

Runs until SIGTERM/SIGINT (clean close: both daemon threads joined),
``--once`` (single scan pass — scripting/tests), or ``--max_promotions N``
resolved publishes. Telemetry events (``promotion_promoted``,
``promotion_rejected``, ``slo_regression``, ``slo_rollback``, ...) append
to the experiment's own JSONL stream so ``tools/telemetry_report.py``
shows the control plane inline with the trainer and the fleet.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_daemon(opts):
    from howtotrainyourmamlpytorch_tpu.serve.resilience.promotion import (
        HttpTarget,
        PromotionConfig,
        PromotionDaemon,
    )

    watch_dir = os.path.abspath(opts.watch)
    exp_dir = os.path.dirname(watch_dir)
    journal = opts.journal or os.path.join(
        exp_dir, "logs", "promotions.jsonl"
    )
    staging = opts.staging or os.path.join(exp_dir, "promotion_staging")
    config = PromotionConfig(
        watch_dir=watch_dir,
        journal_path=journal,
        staging_dir=staging,
        poll_interval_s=opts.poll_interval_s,
        val_stat_key=opts.val_stat,
        require_val_stat=not opts.allow_missing_val_stat,
        val_min_delta=opts.val_min_delta,
        promote_retries=opts.promote_retries,
        promote_backoff_s=opts.promote_backoff_s,
        slo_watch_s=opts.slo_watch_s,
        slo_poll_s=opts.slo_poll_s,
        p99_budget_ms=opts.p99_budget_ms,
        max_error_rate=opts.max_error_rate,
        max_new_nonfinite=opts.max_new_nonfinite,
        min_requests=opts.min_requests,
    )
    return PromotionDaemon(HttpTarget(opts.target), config)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--watch", required=True,
                        help="trainer checkpoint dir (…/saved_models)")
    parser.add_argument("--target", required=True,
                        help="serving front-door base URL "
                        "(http://host:port)")
    parser.add_argument("--journal", default=None,
                        help="promotions journal path (default: "
                        "<exp>/logs/promotions.jsonl)")
    parser.add_argument("--staging", default=None,
                        help="staged-candidate retention dir (default: "
                        "<exp>/promotion_staging)")
    parser.add_argument("--telemetry", default=None,
                        help="telemetry JSONL to append control-plane "
                        "events to (default: <exp>/logs/telemetry.jsonl; "
                        "'none' disables)")
    parser.add_argument("--poll_interval_s", type=float, default=2.0)
    parser.add_argument("--val_stat", default="val_accuracy_mean",
                        help="experiment statistic the val-gate reads")
    parser.add_argument("--val_min_delta", type=float, default=None,
                        help="candidate must beat last-known-good's stat "
                        "by this much (unset: presence-only gate)")
    parser.add_argument("--allow_missing_val_stat", action="store_true",
                        help="promote candidates with no recorded val "
                        "stat (default: reject them)")
    parser.add_argument("--slo_watch_s", type=float, default=10.0)
    parser.add_argument("--slo_poll_s", type=float, default=0.5)
    parser.add_argument("--p99_budget_ms", type=float, default=30_000.0)
    parser.add_argument("--max_error_rate", type=float, default=0.05)
    parser.add_argument("--max_new_nonfinite", type=int, default=0)
    parser.add_argument("--min_requests", type=int, default=1)
    parser.add_argument("--promote_retries", type=int, default=3)
    parser.add_argument("--promote_backoff_s", type=float, default=0.5)
    parser.add_argument("--max_promotions", type=int, default=0,
                        help="exit after N resolved publishes (0 = run "
                        "until signaled)")
    parser.add_argument("--once", action="store_true",
                        help="one scan/process pass, then exit")
    opts = parser.parse_args(argv)

    from howtotrainyourmamlpytorch_tpu.telemetry import events as tel_events
    from howtotrainyourmamlpytorch_tpu.telemetry.events import EventLog

    exp_dir = os.path.dirname(os.path.abspath(opts.watch))
    telemetry_path = opts.telemetry or os.path.join(
        exp_dir, "logs", "telemetry.jsonl"
    )
    sink = None
    if telemetry_path != "none":
        os.makedirs(os.path.dirname(telemetry_path), exist_ok=True)
        sink = EventLog(telemetry_path)
        tel_events.install(sink)
        tel_events.ensure_trace_id()  # join MAML_TRACE_ID when exported

    daemon = build_daemon(opts)
    stop = threading.Event()

    def _graceful(signum, frame):
        stop.set()

    previous = {}
    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            previous[signum] = signal.signal(signum, _graceful)
        except (ValueError, OSError):
            pass
    try:
        if opts.once:
            daemon.slo.start()
            daemon.run_once()
        else:
            daemon.start()
            print(
                f"promotion daemon watching {opts.watch} -> {opts.target} "
                f"(journal {daemon.config.journal_path})",
                flush=True,
            )
            while not stop.is_set():
                if (
                    opts.max_promotions
                    and daemon.resolved_promotions >= opts.max_promotions
                ):
                    break
                stop.wait(0.2)
    finally:
        daemon.close()
        if sink is not None:
            sink.flush()
            tel_events.install(None)
        for signum, handler in previous.items():
            try:
                signal.signal(signum, handler)
            except (ValueError, OSError):
                pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
