"""Autoscaler daemon CLI: journal-backed load-following fleet size.

Watches a serving front door's ``/healthz`` + ``/metrics`` (and
optionally a trainer/fleet heartbeat ``status.json`` for memory
watermarks) against a declared policy, and scales the replica pool
through POST ``/admin/scale`` — every decision journaled BEFORE the
fleet is touched (``logs/autoscale.jsonl``), so SIGKILL at any phase
boundary resumes exactly-once: no double-spawned replica, no orphan.

Usage::

    python tools/autoscaler_daemon.py \
        --target http://127.0.0.1:8080 \
        --journal <experiment>/logs/autoscale.jsonl \
        [--heartbeat <experiment>/logs/status.json] \
        [--min-replicas 1] [--max-replicas 8] \
        [--up-queue-per-replica 4.0] [--up-p99-ms 250] \
        [--down-queue-per-replica 0.5] [--down-p99-ms 50] \
        [--step-up 2] [--step-down 1] [--cooldown-s 5] \
        [--settle-timeout-s 30] [--confirm-samples 2] \
        [--poll-interval-s 1.0] [--telemetry <path>] [--once]

Runs until SIGTERM/SIGINT; ``--once`` drives a single
observe→decide→apply→settle pass (scripting/tests/chaos).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_daemon(opts):
    from howtotrainyourmamlpytorch_tpu.serve.resilience.autoscaler import (
        AutoscalerConfig,
        AutoscalerDaemon,
        AutoscalerPolicy,
        HttpScaleTarget,
    )

    policy = AutoscalerPolicy(
        min_replicas=opts.min_replicas,
        max_replicas=opts.max_replicas,
        up_queue_per_replica=opts.up_queue_per_replica,
        up_p99_ms=opts.up_p99_ms,
        down_queue_per_replica=opts.down_queue_per_replica,
        down_p99_ms=opts.down_p99_ms,
        step_up=opts.step_up,
        step_down=opts.step_down,
        cooldown_s=opts.cooldown_s,
        settle_timeout_s=opts.settle_timeout_s,
        confirm_samples=opts.confirm_samples,
    )
    config = AutoscalerConfig(
        journal_path=os.path.abspath(opts.journal),
        poll_interval_s=opts.poll_interval_s,
        heartbeat_path=opts.heartbeat,
    )
    return AutoscalerDaemon(HttpScaleTarget(opts.target), config, policy)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--target", required=True,
                        help="serving front-door base URL (http://host:port)")
    parser.add_argument("--journal", required=True,
                        help="scale-decision journal path "
                        "(e.g. <exp>/logs/autoscale.jsonl)")
    parser.add_argument("--heartbeat", default=None,
                        help="heartbeat status.json for memory-watermark "
                        "scale-up vetoes (optional)")
    parser.add_argument("--telemetry", default=None,
                        help="telemetry JSONL to append autoscale events "
                        "to ('none'/unset disables)")
    parser.add_argument("--min-replicas", type=int, default=1)
    parser.add_argument("--max-replicas", type=int, default=8)
    parser.add_argument("--up-queue-per-replica", type=float, default=4.0)
    parser.add_argument("--up-p99-ms", type=float, default=250.0)
    parser.add_argument("--down-queue-per-replica", type=float, default=0.5)
    parser.add_argument("--down-p99-ms", type=float, default=50.0)
    parser.add_argument("--step-up", type=int, default=2)
    parser.add_argument("--step-down", type=int, default=1)
    parser.add_argument("--cooldown-s", type=float, default=5.0)
    parser.add_argument("--settle-timeout-s", type=float, default=30.0)
    parser.add_argument("--confirm-samples", type=int, default=2)
    parser.add_argument("--poll-interval-s", type=float, default=1.0)
    parser.add_argument("--once", action="store_true",
                        help="one observe/decide/apply pass, then exit")
    opts = parser.parse_args(argv)

    from howtotrainyourmamlpytorch_tpu.telemetry import events as tel_events
    from howtotrainyourmamlpytorch_tpu.telemetry.events import EventLog

    sink = None
    if opts.telemetry and opts.telemetry != "none":
        os.makedirs(
            os.path.dirname(os.path.abspath(opts.telemetry)), exist_ok=True
        )
        sink = EventLog(opts.telemetry)
        tel_events.install(sink)
        tel_events.ensure_trace_id()

    daemon = build_daemon(opts)
    stop = threading.Event()

    def _graceful(signum, frame):
        stop.set()

    previous = {}
    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            previous[signum] = signal.signal(signum, _graceful)
        except (ValueError, OSError):
            pass
    try:
        if opts.once:
            daemon.run_once()
        else:
            print(
                f"autoscaler watching {opts.target} "
                f"(journal {daemon.config.journal_path})",
                flush=True,
            )
            daemon.run(stop)
    finally:
        if sink is not None:
            sink.flush()
            tel_events.install(None)
        for signum, handler in previous.items():
            try:
                signal.signal(signum, handler)
            except (ValueError, OSError):
                pass
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
