"""Telemetry report: render a run's ``logs/telemetry.jsonl`` + overhead bench.

Report mode — step-time breakdown table (data-wait vs device dispatch vs
host-sync), XLA compile timeline, the device-resource ledger section
(per-program FLOPs/bytes/arithmetic-intensity from ``program_profile``
events, windowed MFU, memory watermarks — absent from pre-ledger logs and
rendered gracefully either way), checkpoint/sentinel/preemption event log::

    python tools/telemetry_report.py <experiment-dir | telemetry.jsonl>
    python tools/telemetry_report.py <run> --json     # machine-readable
    python tools/telemetry_report.py <run> --since <unix-s>   # tail window

Fleet mode — merge multiple ranks' JSONL streams (separate files, a shared
multi-rank file, or both) into ONE ordered timeline with per-rank lanes,
per-dispatch slowest-rank attribution and cross-rank skew stats (the
diagnostic the per-leaf-all-reduce finding in PERF_NOTES.md needed by
hand). Ranks correlate on the run-scoped ``trace_id`` + per-dispatch
``dispatch_id`` the telemetry layer stamps end to end::

    python tools/telemetry_report.py --fleet <run-or-jsonl> [<run...>]
    python tools/telemetry_report.py --fleet <runs...> --json

Overhead bench mode — the ``telemetry_overhead_pct`` key (PERF_NOTES.md
"Telemetry overhead" protocol): drives the REAL K=1 ``run_train_iter`` loop
twice over interleaved timing windows, once plain and once with the full
``TrainTelemetry`` recording path active (per-dispatch step events, forced
reads + buffer flush at the ``TRAIN_LOG_EVERY`` cadence, compile bridge),
and reports the relative throughput cost::

    python tools/telemetry_report.py --overhead-bench [--tiny] [--budget-s 6]

Both variants perform the SAME device work and the same forced reads at the
same cadence, so the delta isolates exactly what telemetry adds: host
timestamping, event buffering, and the boundary flush.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from howtotrainyourmamlpytorch_tpu.telemetry import (  # noqa: E402
    SCHEMA_VERSION,
    EventReader,
    read_events,
)

# ---------------------------------------------------------------------------
# Report mode
# ---------------------------------------------------------------------------


def resolve_jsonl(run: str) -> str:
    """Accepts the JSONL itself, an experiment dir, or its logs/ dir."""
    if os.path.isdir(run):
        for candidate in (
            os.path.join(run, "telemetry.jsonl"),
            os.path.join(run, "logs", "telemetry.jsonl"),
        ):
            if os.path.exists(candidate):
                return candidate
        raise FileNotFoundError(f"no telemetry.jsonl under {run}")
    return run


def _percentiles_ms(samples_s: list[float]) -> dict:
    arr = np.asarray(samples_s, dtype=np.float64) * 1e3
    return {
        "count": int(arr.size),
        "p50_ms": float(np.percentile(arr, 50)),
        "p95_ms": float(np.percentile(arr, 95)),
        "p99_ms": float(np.percentile(arr, 99)),
        "mean_ms": float(np.mean(arr)),
        "total_s": float(np.sum(arr) / 1e3),
    }


def summarize(events: list[dict]) -> dict:
    """The report's data model: per-iteration step breakdown percentiles,
    compile timeline, and the non-step event log. This dict (under
    ``--json``) is the round-trip schema ``tests/test_telemetry.py`` pins."""
    # Timeline origin: the earliest stamp (the schema line is stamped at
    # first FLUSH, which can postdate run_start and the first compiles).
    t0 = min((float(e["t"]) for e in events), default=0.0)
    steps = [e for e in events if e.get("type") == "step"]
    per_iter: dict[str, list[float]] = {
        "step": [], "data_wait": [], "stage_wait": [], "device": [],
    }
    for e in steps:
        k = max(int(e.get("k", 1)), 1)
        per_iter["step"].extend([float(e["step_s"]) / k] * k)
        per_iter["data_wait"].extend([float(e["data_wait_s"]) / k] * k)
        # stage_wait: consumer blocked on a staged device buffer (absent
        # from pre-stager event logs — the row simply drops out then).
        per_iter["stage_wait"].extend(
            [float(e.get("stage_wait_s", 0.0)) / k] * k
        )
        per_iter["device"].extend([float(e["device_s"]) / k] * k)
    syncs = [
        float(e["sync_s"]) for e in events if e.get("type") == "host_sync"
    ]
    breakdown = {
        name: _percentiles_ms(samples)
        for name, samples in per_iter.items()
        if samples
    }
    if syncs:
        breakdown["host_sync"] = _percentiles_ms(syncs)

    compiles = [
        {
            "t_rel_s": round(float(e["t"]) - t0, 3),
            "kind": e["type"],
            "name": e.get("name") or e.get("program", "?"),
        }
        for e in events
        if e.get("type") in ("compile", "serve_compile")
    ]
    log = [
        {
            "t_rel_s": round(float(e["t"]) - t0, 3),
            **{k: v for k, v in e.items() if k not in ("t", "signature")},
        }
        for e in events
        if e.get("type") not in (
            "step", "compile", "serve_compile", "program_profile", "memory",
        )
    ]
    device = _device_section(events, per_iter["step"])
    counts: dict[str, int] = {}
    for e in events:
        counts[e.get("type", "?")] = counts.get(e.get("type", "?"), 0) + 1
    # Mesh attribution (multichip runs): the topology the steps ran on,
    # from the step events themselves (pre-mesh logs default to 1/single).
    n_devices = max(
        (int(e.get("n_devices", 1)) for e in steps), default=1
    )
    mesh_shapes = sorted(
        {str(e.get("mesh_shape", "single")) for e in steps}
    ) or ["single"]
    # Host attribution (multi-host fleets append to one JSONL): which
    # ranks contributed events, out of how many. Pre-multi-host logs
    # default to rank 0 of 1.
    process_count = max(
        (int(e.get("process_count", 1)) for e in events), default=1
    )
    process_indices = sorted(
        {int(e.get("process_index", 0)) for e in events if "process_index" in e}
    ) or [0]
    return {
        "schema": SCHEMA_VERSION,
        "iters": len(per_iter["step"]),
        "n_devices": n_devices,
        "mesh_shape": "+".join(mesh_shapes),
        "process_count": process_count,
        "process_indices": process_indices,
        "breakdown": breakdown,
        "compiles": compiles,
        "device": device,
        "events": log,
        "event_counts": counts,
    }


def _device_section(events: list[dict], step_samples_s: list[float]):
    """The device-resource plane of a run's JSONL: the per-program ledger
    rows (``program_profile`` events — newest per program name wins), the
    last memory watermarks, and the run-level MFU derived from the train
    program's K-corrected FLOPs × the measured iteration rate against the
    peak stamped on the event. ``None`` when the log predates the ledger
    (or telemetry ran without it) — the report renders fine either way,
    the empty-ledger degradation contract."""
    profiles: dict[str, dict] = {}
    for e in events:
        if e.get("type") == "program_profile":
            profiles[str(e.get("name", "?"))] = e
    memories = [e for e in events if e.get("type") == "memory"]
    if not profiles and not memories:
        return None
    section: dict = {
        "programs": [
            {
                key: e.get(key)
                for key in (
                    "name", "role", "k", "flops", "dispatch_flops",
                    "bytes_accessed", "arithmetic_intensity",
                    "hbm_peak_bytes", "temp_bytes", "bucket",
                    "collective_count", "comm_bytes",
                    "device_kind",
                )
            }
            for e in sorted(
                profiles.values(),
                key=lambda p: (str(p.get("role")), str(p.get("name"))),
            )
        ]
    }
    trains = [e for e in profiles.values() if e.get("role") == "train"]
    if trains and step_samples_s and sum(step_samples_s) > 0:
        train = max(trains, key=lambda e: float(e.get("t", 0.0)))
        flops = train.get("flops")
        peak = train.get("peak_flops")
        if flops and peak:
            rate = len(step_samples_s) / sum(step_samples_s)
            # Significant digits, not decimal places: off-TPU MFU sits at
            # 1e-4..1e-6 % and must not round to zero.
            section["mfu_pct"] = float(
                f"{100.0 * rate * flops / peak:.6g}"
            )
            section["peak_flops"] = peak
    if memories:
        last = memories[-1]
        section["memory"] = {
            "devices": last.get("devices"),
            "bytes_in_use_total": last.get("bytes_in_use_total"),
            "peak_bytes_in_use_max": last.get("peak_bytes_in_use_max"),
            "samples": len(memories),
        }
    return section


def render_text(summary: dict) -> str:
    lines = []
    ranks = summary.get("process_indices", [0])
    lines.append(
        f"telemetry report — {summary['iters']} train iterations, "
        f"schema v{summary['schema']}, "
        f"{summary.get('n_devices', 1)} device(s) "
        f"[{summary.get('mesh_shape', 'single')}], "
        f"rank(s) {'+'.join(str(r) for r in ranks)} of "
        f"{summary.get('process_count', 1)} process(es)"
    )
    lines.append("")
    lines.append("step-time breakdown (per iteration)")
    header = (
        f"  {'component':<12} {'count':>7} {'p50 ms':>10} {'p95 ms':>10} "
        f"{'p99 ms':>10} {'mean ms':>10} {'total s':>9}"
    )
    lines.append(header)
    lines.append("  " + "-" * (len(header) - 2))
    for name in ("step", "data_wait", "stage_wait", "device", "host_sync"):
        row = summary["breakdown"].get(name)
        if row is None:
            continue
        lines.append(
            f"  {name:<12} {row['count']:>7} {row['p50_ms']:>10.3f} "
            f"{row['p95_ms']:>10.3f} {row['p99_ms']:>10.3f} "
            f"{row['mean_ms']:>10.3f} {row['total_s']:>9.2f}"
        )
    lines.append("")
    lines.append(f"compile timeline ({len(summary['compiles'])} events)")
    for c in summary["compiles"]:
        lines.append(f"  +{c['t_rel_s']:>9.3f}s  {c['kind']:<14} {c['name']}")
    device = summary.get("device")
    if device:
        lines.append("")
        lines.append(
            f"device-resource ledger ({len(device['programs'])} program(s))"
        )
        dheader = (
            f"  {'program':<22} {'role':<14} {'K':>4} {'flops/iter':>12} "
            f"{'bytes/iter':>12} {'flops/B':>8} {'hbm peak':>12} "
            f"{'coll':>5} {'comm B/iter':>12}"
        )
        lines.append(dheader)
        lines.append("  " + "-" * (len(dheader) - 2))

        def num(value, fmt="{:.3e}"):
            return "—" if value is None else fmt.format(value)

        for row in device["programs"]:
            lines.append(
                f"  {str(row['name'])[:22]:<22} {str(row['role']):<14} "
                f"{row.get('k') or 1:>4} {num(row.get('flops')):>12} "
                f"{num(row.get('bytes_accessed')):>12} "
                f"{num(row.get('arithmetic_intensity'), '{:.2f}'):>8} "
                f"{num(row.get('hbm_peak_bytes')):>12} "
                f"{num(row.get('collective_count'), '{:d}'):>5} "
                f"{num(row.get('comm_bytes'), '{:d}'):>12}"
            )
        if device.get("mfu_pct") is not None:
            lines.append(
                f"  windowed MFU: {device['mfu_pct']:.4g}% of peak "
                f"{device['peak_flops']:.3e} FLOP/s"
            )
        memory = device.get("memory")
        if memory and memory.get("devices"):
            lines.append(
                f"  memory watermarks ({memory['samples']} sample(s)): "
                + ", ".join(
                    f"dev{d.get('device')} in_use="
                    f"{d.get('bytes_in_use', 0):.3e} "
                    f"peak={d.get('peak_bytes_in_use', 0):.3e}"
                    for d in memory["devices"]
                )
            )
    lines.append("")
    lines.append(f"event log ({len(summary['events'])} events)")
    for e in summary["events"]:
        fields = ", ".join(
            f"{k}={v}" for k, v in e.items() if k not in ("t_rel_s", "type")
        )
        lines.append(f"  +{e['t_rel_s']:>9.3f}s  {e['type']:<18} {fields}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Fleet mode: merged multi-rank timeline + cross-rank dispatch attribution
# ---------------------------------------------------------------------------

#: Event types folded into the per-rank step lanes rather than the merged
#: timeline (one line per dispatch would drown the event log).
_LANE_TYPES = ("step",)

#: Timeline length cap in the human rendering — a multi-GB run must not
#: print a multi-GB table.
_TIMELINE_LIMIT = 200

#: Non-step events RETAINED for the merged timeline (the newest ones — a
#: post-mortem reads from the end). Everything still counts into
#: ``event_counts``; bounding retention is what keeps the fleet summary's
#: memory and ``--json`` payload finite on multi-day runs, matching the
#: streaming reader underneath.
_JSON_TIMELINE_LIMIT = 5000


def _rank_of(event: dict, default: int = 0) -> int:
    return int(event.get("process_index", default))


def fleet_events(paths: list[str], since: float | None = None):
    """Streams events from every resolved run path (dirs or JSONL files)
    via the offset-aware reader — multi-GB per-rank logs iterate instead of
    loading whole (a killed writer's complete-but-unterminated last line
    included). A rank may span files AND a file may hold several ranks
    (the shared-logs-dir fleet layout); ``process_index`` on each event is
    the lane key either way."""
    for path in paths:
        reader = EventReader(resolve_jsonl(path))
        yield from reader.iter_events(since=since, include_tail=True)


def fleet_summarize(paths: list[str], since: float | None = None) -> dict:
    """The fleet report's data model (the ``--fleet --json`` schema):
    per-rank step lanes, per-dispatch slowest-rank attribution keyed on
    ``dispatch_id``, cross-rank skew percentiles, trace consistency, and
    the merged non-step timeline (newest ``_JSON_TIMELINE_LIMIT`` events
    retained)."""
    import collections

    lanes: dict[int, dict[str, list[float]]] = {}
    dispatches: dict[object, dict[int, list[dict]]] = {}
    # Device-plane ledger rows, per (rank, program) — a fleet merge shows
    # every rank's compiled-program costs side by side (identical on a
    # healthy lockstep fleet; a divergent row IS the finding).
    programs: dict[tuple[int, str], dict] = {}
    timeline: collections.deque = collections.deque(
        maxlen=_JSON_TIMELINE_LIMIT
    )
    timeline_total = 0
    trace_ids: set[str] = set()
    counts: dict[str, int] = {}
    t0 = None
    for event in fleet_events(paths, since=since):
        etype = event.get("type", "?")
        counts[etype] = counts.get(etype, 0) + 1
        t = float(event.get("t", 0.0))
        t0 = t if t0 is None else min(t0, t)
        if "trace_id" in event:
            trace_ids.add(str(event["trace_id"]))
        if etype == "schema":
            continue
        rank = _rank_of(event)
        if etype in _LANE_TYPES:
            k = max(int(event.get("k", 1)), 1)
            lane = lanes.setdefault(
                rank, {"step": [], "data_wait": [], "stage_wait": [],
                       "device": []}
            )
            lane["step"].extend([float(event["step_s"]) / k] * k)
            lane["data_wait"].extend(
                [float(event.get("data_wait_s", 0.0)) / k] * k
            )
            lane["stage_wait"].extend(
                [float(event.get("stage_wait_s", 0.0)) / k] * k
            )
            lane["device"].extend(
                [float(event.get("device_s", 0.0)) / k] * k
            )
            dispatch_id = event.get("dispatch_id", event.get("iter"))
            if dispatch_id is not None:
                # Per-rank OCCURRENCE LIST, not a single slot: an elastic
                # run replays iterations after a degrade/resume (same
                # dispatch_id, later phase — one trace by design), and a
                # replayed sample must pair with the peer ranks' REPLAY of
                # that iteration, not overwrite a dead phase's entry and
                # fabricate skew against it.
                dispatches.setdefault(dispatch_id, {}).setdefault(
                    rank, []
                ).append({
                    "t": t,
                    "step_s": float(event["step_s"]),
                    "device_s": float(event.get("device_s", 0.0)),
                })
        elif etype == "program_profile":
            programs[(rank, str(event.get("name", "?")))] = {
                "rank": rank,
                **{
                    key: event.get(key)
                    for key in (
                        "name", "role", "k", "flops", "dispatch_flops",
                        "arithmetic_intensity", "hbm_peak_bytes", "bucket",
                    )
                },
            }
        else:
            timeline.append(event)
            timeline_total += 1

    timeline = sorted(timeline, key=lambda e: float(e.get("t", 0.0)))
    t0 = t0 or 0.0

    # Per-dispatch attribution: the i-th occurrence of a dispatch_id on
    # each rank is the same logical dispatch (lockstep fleets replay
    # together); occurrences observed on >= 2 ranks carry cross-rank
    # information — the skew is max-min step time, the slowest rank is
    # the straggler the skew points at.
    skews, slowest_counts = [], {}
    for dispatch_id, per_rank in dispatches.items():
        for occurrence in range(max(len(rows) for rows in per_rank.values())):
            by_step = {
                rank: rows[occurrence]["step_s"]
                for rank, rows in per_rank.items()
                if occurrence < len(rows)
            }
            if len(by_step) < 2:
                continue
            slowest = max(by_step, key=by_step.get)
            skew_s = max(by_step.values()) - min(by_step.values())
            skews.append((dispatch_id, slowest, skew_s))
            slowest_counts[slowest] = slowest_counts.get(slowest, 0) + 1
    skew_values = np.asarray([s for _, _, s in skews], dtype=np.float64)
    skew_stats = (
        {
            "dispatches": int(skew_values.size),
            "p50_ms": float(np.percentile(skew_values, 50) * 1e3),
            "p95_ms": float(np.percentile(skew_values, 95) * 1e3),
            "max_ms": float(np.max(skew_values) * 1e3),
        }
        if skew_values.size
        else {"dispatches": 0}
    )
    worst = sorted(skews, key=lambda row: -row[2])[:5]

    lane_summaries = {
        rank: {
            name: _percentiles_ms(samples)
            for name, samples in lane.items()
            if samples
        }
        for rank, lane in sorted(lanes.items())
    }
    process_count = max(
        [int(e.get("process_count", 1)) for e in timeline] + [len(lanes), 1]
    )
    return {
        "schema": SCHEMA_VERSION,
        "sources": [resolve_jsonl(p) for p in paths],
        "ranks": sorted(lanes),
        "process_count": process_count,
        "trace_ids": sorted(trace_ids),
        # One run-scoped trace across every lane is what makes the merge a
        # single timeline rather than a coincidence of files.
        "trace_consistent": len(trace_ids) <= 1,
        "lanes": lane_summaries,
        "programs": [
            # Plain tuple sort: (rank, name) — str() keys would order
            # rank 10 before rank 2 on wide fleets.
            programs[key] for key in sorted(programs)
        ],
        "dispatch_skew": skew_stats,
        "slowest_rank_dispatches": {
            str(rank): n for rank, n in sorted(slowest_counts.items())
        },
        "worst_dispatches": [
            {
                "dispatch_id": dispatch_id,
                "slowest_rank": rank,
                "skew_ms": round(skew_s * 1e3, 3),
            }
            for dispatch_id, rank, skew_s in worst
        ],
        "t0": t0,
        "timeline_events_total": timeline_total,
        "timeline_truncated": timeline_total > len(timeline),
        "timeline": [
            {
                "t_rel_s": round(float(e.get("t", 0.0)) - t0, 3),
                "rank": _rank_of(e),
                **{
                    key: value
                    for key, value in e.items()
                    if key not in ("t", "signature", "stacks", "trace_id")
                },
            }
            for e in timeline
        ],
        "event_counts": counts,
    }


def render_fleet_text(summary: dict) -> str:
    lines = []
    ranks = summary["ranks"] or [0]
    trace = (
        summary["trace_ids"][0]
        if len(summary["trace_ids"]) == 1
        else f"INCONSISTENT {summary['trace_ids']}"
        if summary["trace_ids"]
        else "(unstamped)"
    )
    lines.append(
        f"fleet telemetry report — {len(summary['sources'])} source(s), "
        f"rank lane(s) {'+'.join(str(r) for r in ranks)} of "
        f"{summary['process_count']}, trace {trace}"
    )
    lines.append("")
    lines.append("per-rank step lanes (per iteration)")
    header = (
        f"  {'rank':<5} {'component':<12} {'count':>7} {'p50 ms':>10} "
        f"{'p95 ms':>10} {'mean ms':>10} {'total s':>9}"
    )
    lines.append(header)
    lines.append("  " + "-" * (len(header) - 2))
    for rank, lane in summary["lanes"].items():
        for name in ("step", "data_wait", "stage_wait", "device"):
            row = lane.get(name)
            if row is None:
                continue
            lines.append(
                f"  {rank:<5} {name:<12} {row['count']:>7} "
                f"{row['p50_ms']:>10.3f} {row['p95_ms']:>10.3f} "
                f"{row['mean_ms']:>10.3f} {row['total_s']:>9.2f}"
            )
    if summary.get("programs"):
        lines.append("")
        lines.append(
            f"device-resource ledger ({len(summary['programs'])} "
            "program row(s) across ranks)"
        )
        for row in summary["programs"]:
            flops = row.get("flops")
            lines.append(
                f"  r{row['rank']}  {str(row.get('name')):<22} "
                f"{str(row.get('role')):<12} K={row.get('k') or 1:<4} "
                + ("flops/iter %.3e" % flops if flops else "flops n/a")
            )
    skew = summary["dispatch_skew"]
    lines.append("")
    if skew.get("dispatches"):
        lines.append(
            f"cross-rank dispatch skew over {skew['dispatches']} shared "
            f"dispatches: p50 {skew['p50_ms']:.3f} ms, "
            f"p95 {skew['p95_ms']:.3f} ms, max {skew['max_ms']:.3f} ms"
        )
        shares = ", ".join(
            f"rank {rank}: {n}"
            for rank, n in summary["slowest_rank_dispatches"].items()
        )
        lines.append(f"slowest-rank attribution (dispatch counts): {shares}")
        for row in summary["worst_dispatches"]:
            lines.append(
                f"  dispatch {row['dispatch_id']}: rank "
                f"{row['slowest_rank']} slowest by {row['skew_ms']:.3f} ms"
            )
    else:
        lines.append(
            "cross-rank dispatch skew: no dispatch observed on >= 2 ranks "
            "(single-rank stream, or pre-dispatch_id logs)"
        )
    lines.append("")
    timeline = summary["timeline"]
    total = summary.get("timeline_events_total", len(timeline))
    shown = timeline[:_TIMELINE_LIMIT]
    lines.append(
        f"merged timeline ({total} events"
        + (f", {len(shown)} shown" if len(shown) < total else "")
        + ")"
    )
    for event in shown:
        fields = ", ".join(
            f"{key}={value}"
            for key, value in event.items()
            if key not in ("t_rel_s", "type", "rank", "metrics")
        )
        lines.append(
            f"  +{event['t_rel_s']:>9.3f}s  r{event['rank']}  "
            f"{event['type']:<18} {fields}"
        )
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# Overhead bench mode (the telemetry_overhead_pct key)
# ---------------------------------------------------------------------------


def _bench_learner(tiny: bool):
    from howtotrainyourmamlpytorch_tpu.models import (
        BackboneConfig,
        MAMLConfig,
        MAMLFewShotLearner,
    )

    if tiny:
        cfg = MAMLConfig(
            backbone=BackboneConfig(
                num_stages=2, num_filters=8, image_height=14, image_width=14,
                num_classes=5, per_step_bn_statistics=True, num_steps=2,
            ),
            number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2,
        )
    else:
        # Flagship bundled run's shapes (bench.py): Omniglot 5-way, 64
        # filters, 5 inner steps, per-step BN.
        cfg = MAMLConfig(
            backbone=BackboneConfig(
                num_stages=4, num_filters=64, image_height=28, image_width=28,
                num_classes=5, per_step_bn_statistics=True, num_steps=5,
            ),
            number_of_training_steps_per_iter=5,
            number_of_evaluation_steps_per_iter=5,
        )
    return MAMLFewShotLearner(cfg)


def _bench_batch(learner, batch_size: int, rng):
    bb = learner.cfg.backbone
    way = bb.num_classes
    img = (bb.image_channels, bb.image_height, bb.image_width)
    xs = rng.rand(batch_size, way, 1, *img).astype(np.float32)
    ys = np.tile(
        np.arange(way, dtype=np.int32)[None, :, None], (batch_size, 1, 1)
    )
    return xs, xs.copy(), ys, ys.copy()


def measure_overhead(
    tiny: bool = True,
    budget_s: float = 6.0,
    windows: int = 3,
    batch_size: int = 2,
    logs_dir: str | None = None,
) -> dict:
    """Interleaved plain/telemetry timing windows over the real K=1 train
    step; returns the result dict (median rates + overhead pct)."""
    import tempfile

    import jax

    # The REAL loop's forced-read cadence — imported, not re-declared, so
    # the bench can't silently drift from the trainer.
    from howtotrainyourmamlpytorch_tpu.experiment_builder import (
        TRAIN_LOG_EVERY,
    )
    from howtotrainyourmamlpytorch_tpu.telemetry import TrainTelemetry

    learner = _bench_learner(tiny)
    rng = np.random.RandomState(0)
    batch = _bench_batch(learner, batch_size, rng)
    state = learner.init_state(jax.random.PRNGKey(0))
    state, losses = learner.run_train_iter(state, batch, epoch=0)  # compile
    jax.block_until_ready(state.theta)

    logs_dir = logs_dir or tempfile.mkdtemp(prefix="telemetry_overhead_")

    def run_window(seconds: float, telemetry: TrainTelemetry | None):
        nonlocal state
        n = 0
        loss = losses.get("loss")
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < seconds:
            state, step_losses = learner.run_train_iter(state, batch, epoch=0)
            loss = step_losses.get("loss")
            n += 1
            if telemetry is not None:
                telemetry.record_dispatch(n, n_iters=1, data_wait_s=0.0)
            if n % TRAIN_LOG_EVERY == 0:
                # BOTH variants pay the same forced read at the same
                # cadence (the real loop's log/sentinel sync); only the
                # boundary bookkeeping + flush differs.
                t_sync = time.perf_counter()
                jax.device_get(loss)
                sync_s = time.perf_counter() - t_sync
                if telemetry is not None:
                    telemetry.boundary(n, sync_s, reason="log")
        jax.block_until_ready(state.theta)
        return n / (time.perf_counter() - t0)

    per_window = budget_s / (2 * windows)
    plain_rates, telemetry_rates, pair_overheads = [], [], []
    telemetry = TrainTelemetry(logs_dir, enabled=True)
    with telemetry.activate():
        for w in range(windows):
            # PAIRED windows: each pair runs back-to-back so its overhead
            # delta sees the same machine state; the pair's order
            # alternates so slow drift (thermal, co-tenant load) cancels
            # across pairs instead of biasing one side. The reported value
            # is the median of per-pair deltas — the per-iteration
            # telemetry cost (~µs) is far below window-to-window noise on
            # a shared host, so an unpaired median-of-rates comparison
            # just measures that noise.
            order = (None, telemetry) if w % 2 == 0 else (telemetry, None)
            pair = {}
            for variant in order:
                rate = run_window(per_window, variant)
                if variant is None:
                    plain_rates.append(rate)
                    pair["plain"] = rate
                else:
                    telemetry_rates.append(rate)
                    pair["telemetry"] = rate
            pair_overheads.append(
                (pair["plain"] - pair["telemetry"]) / pair["plain"] * 100.0
            )
    plain = statistics.median(plain_rates)
    instrumented = statistics.median(telemetry_rates)
    overhead_pct = statistics.median(pair_overheads)
    return {
        "metric": "telemetry_overhead_pct",
        "value": round(overhead_pct, 3),
        "unit": "%",
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "tiny": bool(tiny),
        "plain_iters_per_s": round(plain, 3),
        "telemetry_iters_per_s": round(instrumented, 3),
        "pair_overheads_pct": [round(o, 3) for o in pair_overheads],
        "windows": windows,
        "events_logged": os.path.exists(
            os.path.join(logs_dir, "telemetry.jsonl")
        ),
    }


# ---------------------------------------------------------------------------


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description="Render a run's telemetry JSONL, or measure the "
        "telemetry_overhead_pct bench key"
    )
    parser.add_argument("run", nargs="?", default=None,
                        help="experiment dir or telemetry.jsonl path")
    parser.add_argument("--fleet", nargs="+", metavar="RUN",
                        help="merge multiple ranks' runs/JSONLs into one "
                             "timeline with per-rank lanes, per-dispatch "
                             "slowest-rank attribution and skew stats")
    parser.add_argument("--since", type=float, default=None,
                        help="only events stamped at/after this unix time "
                             "(streams from the offset-aware reader)")
    parser.add_argument("--json", action="store_true",
                        help="machine-readable summary instead of tables")
    parser.add_argument("--overhead-bench", action="store_true",
                        help="measure telemetry_overhead_pct on the real "
                             "K=1 train step (one JSON line)")
    parser.add_argument("--tiny", action="store_true",
                        help="overhead bench: CI-sized model (the CPU "
                             "protocol) instead of the flagship shapes")
    parser.add_argument("--budget-s", type=float, default=6.0)
    parser.add_argument("--windows", type=int, default=3)
    opts = parser.parse_args(argv)

    if opts.overhead_bench:
        print(json.dumps(
            measure_overhead(
                tiny=opts.tiny, budget_s=opts.budget_s, windows=opts.windows
            )
        ))
        return 0
    if opts.fleet:
        paths = list(opts.fleet) + ([opts.run] if opts.run else [])
        summary = fleet_summarize(paths, since=opts.since)
        print(json.dumps(summary) if opts.json
              else render_fleet_text(summary))
        return 0
    if not opts.run:
        parser.error("a run path is required unless "
                     "--overhead-bench/--fleet")
    summary = summarize(
        read_events(resolve_jsonl(opts.run), since=opts.since)
    )
    if opts.json:
        print(json.dumps(summary))
    else:
        print(render_text(summary))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
