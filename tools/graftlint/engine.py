"""Lint driver: file discovery, rule execution, suppression, formatting."""

from __future__ import annotations

import os

from .core import ModuleFile, Project, Violation, apply_suppressions
from .rules import ALL_RULES, Rule

RULES: dict[str, Rule] = {rule.id: rule for rule in ALL_RULES}

#: rule ids valid in a ``# graftlint: disable=`` comment.
KNOWN_RULE_IDS = set(RULES) | {"bad-suppression"}

_EXCLUDED_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def _collect_files(paths: list[str]) -> list[str]:
    files: list[str] = []
    for path in paths:
        if os.path.isfile(path):
            files.append(path)
            continue
        for dirpath, dirnames, filenames in os.walk(path):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDED_DIRS]
            for f in sorted(filenames):
                if f.endswith(".py"):
                    files.append(os.path.join(dirpath, f))
    return sorted(dict.fromkeys(files))


def _lint_project(project: Project) -> list[Violation]:
    out: list[Violation] = []
    for module in project.modules:
        found: list[Violation] = []
        for rule in ALL_RULES:
            found.extend(rule.check(module, project))
        out.extend(apply_suppressions(module, found, KNOWN_RULE_IDS))
    return sorted(set(out), key=lambda v: (v.path, v.line, v.col, v.rule))


def lint_sources(sources: dict[str, str]) -> list[Violation]:
    """Lints in-memory ``{path: source}`` pairs (the unit-test entry point).
    Unparseable files produce a ``parse-error`` violation rather than a
    crash."""
    modules: list[ModuleFile] = []
    errors: list[Violation] = []
    for path, source in sources.items():
        try:
            modules.append(ModuleFile.parse(path, source))
        except SyntaxError as exc:
            errors.append(
                Violation(
                    rule="parse-error",
                    path=path,
                    line=exc.lineno or 0,
                    col=exc.offset or 0,
                    message=f"could not parse: {exc.msg}",
                )
            )
    return errors + _lint_project(Project(modules=modules))


def lint_source(source: str, path: str = "<string>.py") -> list[Violation]:
    """Lints one in-memory module."""
    return lint_sources({path: source})


def lint_paths(paths: list[str]) -> list[Violation]:
    """Lints every ``*.py`` under the given files/directories."""
    sources: dict[str, str] = {}
    for f in _collect_files(paths):
        with open(f, encoding="utf-8") as fh:
            sources[f] = fh.read()
    return lint_sources(sources)
