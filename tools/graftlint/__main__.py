"""CLI: ``python -m tools.graftlint [paths...] [--format=text|github]``.

Exits non-zero when any violation is found, so the tier-1 gate
(``tests/test_graftlint_clean.py``) and any CI step can invoke it directly.
``--format=github`` emits GitHub Actions ``::error`` annotations.
"""

from __future__ import annotations

import argparse
import sys

from .engine import RULES, lint_paths


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m tools.graftlint",
        description="JAX-aware static analysis for this codebase",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        default=["howtotrainyourmamlpytorch_tpu", "tests", "tools"],
        help="files or directories to lint (default: the tier-1 surface)",
    )
    parser.add_argument(
        "--format",
        choices=("text", "github"),
        default="text",
        help="output style: human text or GitHub Actions annotations",
    )
    parser.add_argument(
        "--select",
        default="",
        help="comma-separated rule ids to run (default: all)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule set and exit"
    )
    parser.add_argument(
        "--programs",
        action="store_true",
        help=(
            "trace the registered learner programs (models/common."
            "registered_programs) and run the IR-level program rules "
            "instead of the AST rules; prints the program table when clean"
        ),
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule_id in sorted(RULES):
            print(f"{rule_id}: {RULES[rule_id].summary}")
        return 0

    selected: set[str] | None = None
    if args.select:
        selected = {r.strip() for r in args.select.split(",") if r.strip()}
        unknown = selected - set(RULES) - {"bad-suppression", "parse-error"}
        if unknown:
            print(f"unknown rule id(s): {sorted(unknown)}", file=sys.stderr)
            return 2

    if args.programs:
        # The registry's mesh variants need multiple devices; configure
        # the virtual-CPU platform before anything imports jax.
        from .programs import (
            analyze_registry,
            ensure_cpu_devices,
            lint_programs,
            render_program_table,
        )

        ensure_cpu_devices(8)
        analyses = analyze_registry()
        program_violations = lint_programs(selected, analyses)
        for v in program_violations:
            print(
                v.format_github()
                if args.format == "github"
                else v.format_text()
            )
        if program_violations:
            print(
                f"\ngraftlint: {len(program_violations)} program "
                f"violation(s) across {len(analyses)} traced program(s)",
                file=sys.stderr,
            )
            return 1
        print(render_program_table(analyses))
        print(
            f"graftlint: {len(analyses)} program(s) clean", file=sys.stderr
        )
        return 0

    violations = lint_paths(args.paths)
    if selected is not None:
        violations = [v for v in violations if v.rule in selected]

    for v in violations:
        print(v.format_github() if args.format == "github" else v.format_text())
    if violations:
        print(
            f"\ngraftlint: {len(violations)} violation(s) in "
            f"{len({v.path for v in violations})} file(s)",
            file=sys.stderr,
        )
        return 1
    print("graftlint: clean", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
