"""Shared AST analysis: import resolution, traced-function discovery, taint.

Everything here is heuristic by design — graftlint trades soundness for
zero-dependency, zero-execution analysis of one module at a time. The two
load-bearing ideas:

* **Traced-function discovery.** A function is *traced* when JAX may call it
  under ``jit``/``grad``/``vmap``/``scan``/... — seeded from decorator and
  call sites (``jax.jit(f)``, ``lax.scan(body, ...)``, ``@jax.jit``,
  ``functools.partial(jax.jit, ...)``) and closed transitively over the
  module-local call graph (a helper called from a traced function runs under
  the same trace).

* **Taint.** Within a traced function, names holding (likely) tracer values:
  results of ``jnp.``/``lax.``/``jax.`` calls, anything assigned from a
  tainted expression, and (optionally) the function's own parameters.
  Iterated to a fixpoint so statement order doesn't matter.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

#: Last attribute component of callables that trace a callable argument,
#: valid only under a JAX namespace root (see :func:`resolve_dotted`).
TRACE_WRAPPER_TAILS = {
    "jit",
    "pjit",
    "pmap",
    "vmap",
    "grad",
    "value_and_grad",
    "checkpoint",
    "remat",
    "custom_jvp",
    "custom_vjp",
    "scan",
    "while_loop",
    "fori_loop",
    "cond",
    "switch",
    "map",
    "associative_scan",
}

#: Namespace roots under which the tails above count as tracers.
JAX_ROOTS = ("jax", "jax.lax", "jax.numpy", "jax.experimental.pjit")

#: Namespace roots whose call results are treated as device/tracer values.
DEVICE_ROOTS = ("jax.numpy", "jax.lax", "jax.nn", "jax.random", "jax.scipy")


def build_alias_map(tree: ast.Module) -> dict[str, str]:
    """Maps local names to fully-dotted module paths from import statements
    (``import jax.numpy as jnp`` -> ``{"jnp": "jax.numpy"}``,
    ``from jax import lax`` -> ``{"lax": "jax.lax"}``)."""
    aliases: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0]
                )
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for a in node.names:
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def resolve_dotted(node: ast.AST, aliases: dict[str, str]) -> str | None:
    """Fully-resolved dotted path of a Name/Attribute chain (``jnp.mean`` ->
    ``jax.numpy.mean``), or None for non-chain expressions."""
    dotted = dotted_name(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    head = aliases.get(head, head)
    return f"{head}.{rest}" if rest else head


def _under_root(resolved: str | None, roots: tuple[str, ...]) -> bool:
    if resolved is None:
        return False
    return any(resolved == r or resolved.startswith(r + ".") for r in roots)


def is_trace_entry(call_func: ast.AST, aliases: dict[str, str]) -> bool:
    """Whether a Call's func is a JAX transform that traces callable args.

    ``jax.tree.map``/``tree_util.tree_map`` are deliberately NOT entries:
    their callbacks run eagerly host-side outside a trace (idiomatic with
    numpy in tests). When a tree.map sits inside an already-traced function
    its callback body is still scanned — nested lambdas are walked as part
    of the enclosing traced function.
    """
    resolved = resolve_dotted(call_func, aliases)
    if resolved is None:
        return False
    root, _, tail = resolved.rpartition(".")
    if root in ("jax.tree", "jax.tree_util"):
        return False
    return tail in TRACE_WRAPPER_TAILS and _under_root(root or resolved, JAX_ROOTS)


def is_device_call(call: ast.Call, aliases: dict[str, str]) -> bool:
    """Whether a call's result is (likely) a tracer/device value."""
    return _under_root(resolve_dotted(call.func, aliases), DEVICE_ROOTS)


def unwrap_partial(node: ast.AST, aliases: dict[str, str]) -> tuple[ast.AST, bool]:
    """Peels ``functools.partial(f, ...)`` layers; returns ``(innermost,
    was_partial)``."""
    was_partial = False
    while (
        isinstance(node, ast.Call)
        and resolve_dotted(node.func, aliases) in ("functools.partial", "partial")
        and node.args
    ):
        node = node.args[0]
        was_partial = True
    return node, was_partial


def _callable_ref_names(node: ast.AST, aliases: dict[str, str]) -> list[str]:
    """Bare names a callable-reference expression points at: ``f`` -> [f],
    ``self._train_step`` -> [_train_step], ``functools.partial(f, ...)`` ->
    [f]. Lambdas return [] (handled as nodes, not names)."""
    node, _ = unwrap_partial(node, aliases)
    if isinstance(node, ast.Name):
        return [node.id]
    if isinstance(node, ast.Attribute):
        # Only own-module references: self.method / cls.method. A dotted
        # library path (optax.adam) resolves and is skipped.
        if isinstance(node.value, ast.Name) and node.value.id in ("self", "cls"):
            return [node.attr]
    return []


@dataclass
class TraceInfo:
    """Traced-function analysis for one module."""

    traced_names: set[str] = field(default_factory=set)
    traced_nodes: set[int] = field(default_factory=set)  # id() of def/lambda
    _defs_by_name: dict[str, list[ast.AST]] = field(default_factory=dict)

    def is_traced(self, node: ast.AST) -> bool:
        if id(node) in self.traced_nodes:
            return True
        name = getattr(node, "name", None)
        return name is not None and name in self.traced_names


def analyze_tracing(tree: ast.Module, aliases: dict[str, str]) -> TraceInfo:
    info = TraceInfo()
    defs: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, []).append(node)
    info._defs_by_name = defs

    seed_names: set[str] = set()
    seed_lambdas: list[ast.Lambda] = []

    def seed_callable(arg: ast.AST) -> None:
        inner, _ = unwrap_partial(arg, aliases)
        if isinstance(inner, ast.Lambda):
            seed_lambdas.append(inner)
        else:
            seed_names.update(_callable_ref_names(arg, aliases))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and is_trace_entry(node.func, aliases):
            for arg in node.args:
                seed_callable(arg)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if is_trace_entry(target, aliases):
                    seed_names.add(node.name)
                # functools.partial(jax.jit, ...) used as a decorator
                if (
                    isinstance(dec, ast.Call)
                    and resolve_dotted(dec.func, aliases)
                    in ("functools.partial", "partial")
                    and dec.args
                    and is_trace_entry(dec.args[0], aliases)
                ):
                    seed_names.add(node.name)

    # Transitive closure over the module-local call graph: every name called
    # (or referenced as a callable) inside a traced function is traced too.
    info.traced_names = set(seed_names)
    for lam in seed_lambdas:
        info.traced_nodes.add(id(lam))

    def called_names(fn: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                out.update(_callable_ref_names(node.func, aliases))
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    out.update(_callable_ref_names(arg, aliases))
        return out

    frontier: list[ast.AST] = list(seed_lambdas)
    for name in seed_names:
        frontier.extend(defs.get(name, []))
    seen_ids = {id(f) for f in frontier}
    while frontier:
        fn = frontier.pop()
        info.traced_nodes.add(id(fn))
        for name in called_names(fn):
            if name in info.traced_names:
                continue
            if name in defs:
                info.traced_names.add(name)
                for d in defs[name]:
                    if id(d) not in seen_ids:
                        seen_ids.add(id(d))
                        frontier.append(d)
    return info


def iter_traced_functions(tree: ast.Module, info: TraceInfo):
    """Yields every FunctionDef/Lambda node the analysis marked traced."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            if info.is_traced(node):
                yield node


def param_names(fn: ast.AST) -> list[str]:
    args = fn.args
    names = [a.arg for a in args.posonlyargs + args.args + args.kwonlyargs]
    if args.vararg:
        names.append(args.vararg.arg)
    if args.kwarg:
        names.append(args.kwarg.arg)
    return [n for n in names if n not in ("self", "cls")]


def _assigned_names(target: ast.AST) -> set[str]:
    out: set[str] = set()
    for node in ast.walk(target):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            out.add(node.id)
    return out


def taint_names(
    fn: ast.AST, aliases: dict[str, str], include_params: bool
) -> set[str]:
    """Names (likely) bound to tracer/device values inside ``fn``, computed
    to a fixpoint over the function's assignments. Nested function bodies are
    included — their device results flow through the same local names often
    enough that excluding them loses real findings."""
    tainted: set[str] = set(param_names(fn)) if include_params else set()

    def expr_tainted(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call) and is_device_call(node, aliases):
                return True
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in tainted:
                    return True
        return False

    changed = True
    while changed:
        changed = False
        for node in ast.walk(fn):
            targets: list[ast.AST] = []
            value: ast.AST | None = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)) and node.value:
                targets, value = [node.target], node.value
            elif isinstance(node, ast.For):
                targets, value = [node.target], node.iter
            if value is None or not expr_tainted(value):
                continue
            for t in targets:
                new = _assigned_names(t) - tainted
                if new:
                    tainted |= new
                    changed = True
    return tainted


def expr_references_taint(
    expr: ast.AST, tainted: set[str], aliases: dict[str, str]
) -> bool:
    """Whether an expression touches a tainted name or a direct device call."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            if node.id in tainted:
                return True
        if isinstance(node, ast.Call) and is_device_call(node, aliases):
            return True
    return False
