"""graftlint — JAX-aware static analysis for this codebase.

An AST-based lint framework targeting the silent-failure classes that
golden-run archaeology kept finding by accident (PR 1's GSPMD truncation,
the reference's dropped second-order terms): reused PRNG keys, host-numpy
on tracers, Python control flow on traced values, recompile hazards,
missing donation on train steps, dead CLI flags, device ops in the host
data path, and state mutation inside traced functions.

Run the CLI::

    python -m tools.graftlint howtotrainyourmamlpytorch_tpu/ tests/ tools/

Suppress a finding inline (the reason is mandatory — an unreasoned
suppression is itself a violation)::

    some_code()  # graftlint: disable=<rule-id> -- why this is safe

Library API: :func:`lint_paths`, :func:`lint_sources`, :func:`lint_source`
return :class:`Violation` lists; ``RULES`` maps rule id -> rule object.
``tests/test_graftlint_clean.py`` runs the CLI over the whole tree in
tier-1, so the package lints clean by construction.
"""

from .engine import (  # noqa: F401
    RULES,
    Violation,
    lint_paths,
    lint_source,
    lint_sources,
)

__all__ = ["RULES", "Violation", "lint_paths", "lint_source", "lint_sources"]
