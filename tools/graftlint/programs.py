"""graftlint v3: IR-level program contract analysis (ISSUE 17).

The AST rules see source; the costliest defects in a MAML++
reverse-over-reverse step only exist in the *lowered program* — the
~147 per-leaf all-reduce storm (PERF_NOTES.md "Pod-scale multi-host
protocol"), f32 leaks inside a declared-bf16 compute region, donated
buffers XLA silently failed to alias, host callbacks reachable from a
hot loop. This pass traces every program the learner-side registry
(``models/common.registered_programs``) declares — ``jax.make_jaxpr``
plus (for donation) a cache-hit ``lower()``: zero devices touched, zero
executions — walks the IR once, and feeds five rules:

* ``collective-budget`` — explicit collectives (psum / all-gather /
  reduce-scatter / ...) per meta-iteration vs the budget the learner
  declares in code (``collective_budget`` class attr). Scan bodies are
  walked ONCE, mirroring ``dispatch_multiplier``'s accounting: the walk
  count IS the per-meta-iteration count for the K-scan form.
* ``dtype-leak`` — a dot/conv with a float32 operand inside a
  declared-bf16 program. The PR 9 boundary casts and the f32-master
  update chain are allowlisted by construction: casts are not matmuls
  and Adam contains none, so a clean bf16 program has ZERO f32
  contractions (measured; tests/test_graftlint_programs.py pins both
  directions).
* ``donation-violation`` — a program whose registry entry declares
  donation but whose lowered module aliases fewer inputs than the
  donated argument has leaves (``tf.aliasing_output``).
* ``host-callback-in-step`` — ``pure_callback``/``io_callback``/
  ``debug_callback`` reachable anywhere in a registered (hot) program.
* ``spec-coverage`` — the sharding tables' static twin: every state
  leaf of every learner family matches a partition rule, and every rule
  matches at least one leaf somewhere (the dead-rule class, mirroring
  ``dead-flag``).

This module must stay importable WITHOUT jax (the graftlint CLI runs as
a subprocess many times per tier-1 session); everything that traces is
lazy inside the analysis entry points.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Iterator

from .core import Violation
from .rules import Rule

#: Explicit cross-replica collective primitives (jaxpr names). GSPMD's
#: layout-driven implicit collectives never appear in a jaxpr — which is
#: exactly why the fused dp step makes its reduction explicit
#: (parallel/collectives.py): countable, budgetable, lintable.
COLLECTIVE_PRIMITIVES = frozenset({
    "psum", "psum2", "all_gather", "all_to_all", "ppermute", "pmin",
    "pmax", "reduce_scatter", "psum_scatter", "pgather",
})

CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
})

CONTRACTION_PRIMITIVES = frozenset({
    "dot_general", "conv_general_dilated",
})


@dataclasses.dataclass
class CollectiveOp:
    """One explicit collective in a program's jaxpr (body-once walk)."""

    primitive: str
    nbytes: int


@dataclasses.dataclass
class ProgramAnalysis:
    """Everything the program rules read about ONE registered program."""

    spec: Any  # models/common.ProgramSpec
    collectives: list[CollectiveOp] = dataclasses.field(default_factory=list)
    f32_contractions: dict[str, int] = dataclasses.field(default_factory=dict)
    callbacks: dict[str, int] = dataclasses.field(default_factory=dict)
    donated_leaves: int | None = None
    aliased_outputs: int | None = None
    error: str | None = None

    @property
    def collective_count(self) -> int:
        return len(self.collectives)

    @property
    def comm_bytes(self) -> int:
        return sum(op.nbytes for op in self.collectives)


def walk_jaxpr(jaxpr, visit) -> None:
    """Calls ``visit(eqn)`` for every equation reachable from ``jaxpr``,
    descending into sub-jaxprs carried in equation params (pjit, scan,
    cond branches, shard_map, remat, custom_vjp). Each sub-jaxpr is
    walked once per reference — a ``lax.scan`` BODY therefore counts
    once, the ``dispatch_multiplier`` convention every per-iteration
    consumer shares."""
    for eqn in jaxpr.eqns:
        visit(eqn)
        for value in eqn.params.values():
            candidates = (
                value if isinstance(value, (tuple, list)) else (value,)
            )
            for cand in candidates:
                inner = getattr(cand, "jaxpr", None)
                if inner is not None and hasattr(inner, "eqns"):
                    walk_jaxpr(inner, visit)
                elif hasattr(cand, "eqns"):
                    walk_jaxpr(cand, visit)


def _aval_bytes(var) -> int:
    aval = getattr(var, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    size = 1
    for dim in shape:
        try:
            size *= int(dim)
        except TypeError:  # symbolic dim
            return 0
    return size * dtype.itemsize


def analyze_program(spec) -> ProgramAnalysis:
    """Abstractly traces one registered program and walks its IR once.

    ``jax.make_jaxpr`` for the primitive-level facts; when the spec
    declares donation, an AOT ``lower()`` (no compile, no devices) for
    the ``tf.aliasing_output`` markers. Trace failures degrade to an
    ``error`` the rules surface instead of crashing the lint run."""
    import jax

    analysis = ProgramAnalysis(spec=spec)
    try:
        fn, args = spec.build()
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as exc:  # noqa: BLE001 — surfaced as a lint finding
        analysis.error = f"{type(exc).__name__}: {exc}"
        return analysis

    bf16 = spec.compute_dtype == "bfloat16"

    def visit(eqn):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMITIVES:
            analysis.collectives.append(CollectiveOp(
                primitive=name,
                nbytes=sum(_aval_bytes(v) for v in eqn.invars),
            ))
        elif name in CALLBACK_PRIMITIVES:
            analysis.callbacks[name] = analysis.callbacks.get(name, 0) + 1
        elif bf16 and name in CONTRACTION_PRIMITIVES:
            if any(
                str(getattr(v.aval, "dtype", "")) == "float32"
                for v in eqn.invars
            ):
                analysis.f32_contractions[name] = (
                    analysis.f32_contractions.get(name, 0) + 1
                )

    walk_jaxpr(closed.jaxpr, visit)

    if spec.donate:
        analysis.donated_leaves = len(jax.tree.leaves(args[0]))
        try:
            text = fn.lower(*args).as_text()
            # Unsharded lowerings resolve aliasing eagerly
            # (tf.aliasing_output per donated input); sharded lowerings
            # defer the pairing to XLA and mark donors as
            # jax.buffer_donor. Both honor the donation contract.
            analysis.aliased_outputs = text.count(
                "tf.aliasing_output"
            ) + text.count("jax.buffer_donor")
        except Exception as exc:  # noqa: BLE001 — surfaced by the rule
            analysis.error = f"lowering failed: {type(exc).__name__}: {exc}"
    return analysis


def analyze_registry() -> list[ProgramAnalysis]:
    """Analyses for every program the learner-side registry can build in
    this process (device-count-dependent mesh variants included)."""
    from howtotrainyourmamlpytorch_tpu.models.common import (
        registered_programs,
    )

    return [analyze_program(spec) for spec in registered_programs()]


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------


class ProgramRule(Rule):
    """A rule over traced programs. The AST hook is a registered no-op —
    program rules ride ``ALL_RULES`` for ``--list-rules``/README-sync/
    ``--select`` parity, but only fire through ``lint_programs``."""

    def check(self, module, project) -> Iterator[Violation]:
        return iter(())

    def check_program(self, analysis: ProgramAnalysis) -> Iterator[Violation]:
        return iter(())

    def check_registry(
        self, analyses: list[ProgramAnalysis]
    ) -> Iterator[Violation]:
        return iter(())

    def _pv(self, analysis: ProgramAnalysis, message: str) -> Violation:
        return Violation(
            rule=self.id,
            path=analysis.spec.source,
            line=analysis.spec.line,
            col=0,
            message=f"[{analysis.spec.name}] {message}",
        )


class CollectiveBudgetRule(ProgramRule):
    id = "collective-budget"
    summary = (
        "a program's explicit per-meta-iteration collective count (scan "
        "bodies once x declared dispatch multiplier) exceeds the budget "
        "the learner declares in code (collective_budget)"
    )

    def check_program(self, analysis):
        budget = analysis.spec.collective_budget
        count = analysis.collective_count
        if count > budget:
            by_prim: dict[str, int] = {}
            for op in analysis.collectives:
                by_prim[op.primitive] = by_prim.get(op.primitive, 0) + 1
            detail = ", ".join(
                f"{name} x{n}" for name, n in sorted(by_prim.items())
            )
            yield self._pv(
                analysis,
                f"{count} explicit collectives per meta-iteration "
                f"({detail}; {analysis.comm_bytes} bytes) exceed the "
                f"declared collective_budget of {budget} — fuse the "
                "reduction into flat dtype buckets "
                "(parallel/collectives.fused_psum)",
            )


class DtypeLeakRule(ProgramRule):
    id = "dtype-leak"
    summary = (
        "a dot/conv consumes float32 operands inside a declared-bf16 "
        "program — an f32 leak in the compute region (boundary casts and "
        "the f32-master update chain contain no contractions and never "
        "trip this)"
    )

    def check_program(self, analysis):
        if analysis.spec.compute_dtype != "bfloat16":
            return
        if analysis.f32_contractions:
            detail = ", ".join(
                f"{name} x{n}"
                for name, n in sorted(analysis.f32_contractions.items())
            )
            yield self._pv(
                analysis,
                f"float32 contractions in a declared-bf16 program "
                f"({detail}) — an operand escaped the compute-dtype "
                "boundary cast (models/common.cast_floats)",
            )


class DonationViolationRule(ProgramRule):
    id = "donation-violation"
    summary = (
        "a program declared as donating its state aliases fewer inputs "
        "than the donated argument has leaves (tf.aliasing_output in the "
        "lowered module) — XLA dropped the in-place update"
    )

    def check_program(self, analysis):
        if not analysis.spec.donate:
            return
        if analysis.error and analysis.aliased_outputs is None:
            yield self._pv(
                analysis,
                f"donation unverifiable — {analysis.error}",
            )
            return
        donated = analysis.donated_leaves or 0
        aliased = analysis.aliased_outputs or 0
        if aliased < donated:
            yield self._pv(
                analysis,
                f"only {aliased} of {donated} donated state leaves are "
                "aliased to outputs in the lowered program — the "
                "unaliased leaves double-buffer every dispatch",
            )


class HostCallbackInStepRule(ProgramRule):
    id = "host-callback-in-step"
    summary = (
        "a pure_callback/io_callback/debug_callback is reachable in a "
        "registered hot program — every dispatch would sync to the host"
    )

    def check_program(self, analysis):
        if analysis.callbacks:
            detail = ", ".join(
                f"{name} x{n}"
                for name, n in sorted(analysis.callbacks.items())
            )
            yield self._pv(
                analysis,
                f"host callback reachable in a hot program ({detail}) — "
                "hoist it out of the step or gate it behind a debug "
                "build",
            )


class SpecCoverageRule(ProgramRule):
    id = "spec-coverage"
    summary = (
        "the partition-rule tables and the learners' states disagree: a "
        "state leaf no rule matches, or a rule no leaf of any learner "
        "family matches (the dead-rule class)"
    )

    #: Source anchor for table-level findings.
    TABLES_PATH = "howtotrainyourmamlpytorch_tpu/parallel/sharding.py"

    def _table_violation(self, pattern: str, message: str) -> Violation:
        line = 1
        try:
            with open(self.TABLES_PATH, encoding="utf-8") as fh:
                for lineno, text in enumerate(fh, start=1):
                    if pattern in text:
                        line = lineno
                        break
        except OSError:
            pass
        return Violation(
            rule=self.id, path=self.TABLES_PATH, line=line, col=0,
            message=message,
        )

    def check_registry(self, analyses):
        del analyses  # table-level, not per-program
        import re as _re

        import jax

        from howtotrainyourmamlpytorch_tpu.models import (
            MAMLFewShotLearner,
        )
        from howtotrainyourmamlpytorch_tpu.models.anil import ANILLearner
        from howtotrainyourmamlpytorch_tpu.models.common import (
            _tiny_backbone_kwargs,
        )
        from howtotrainyourmamlpytorch_tpu.models.gradient_descent import (
            GradientDescentLearner,
        )
        from howtotrainyourmamlpytorch_tpu.models.maml import (
            BackboneConfig, MAMLConfig,
        )
        from howtotrainyourmamlpytorch_tpu.models.matching_nets import (
            MatchingNetsLearner,
        )
        from howtotrainyourmamlpytorch_tpu.models.protonets import (
            ProtoNetsLearner,
        )
        from howtotrainyourmamlpytorch_tpu.parallel.sharding import (
            DP_STATE_RULES, MP_STATE_RULES, tree_path_name,
        )
        from jax.tree_util import tree_flatten_with_path

        def cfg(**backbone_overrides):
            kwargs = _tiny_backbone_kwargs()
            kwargs.update(backbone_overrides)
            return MAMLConfig(
                backbone=BackboneConfig(**kwargs),
                number_of_training_steps_per_iter=2,
                number_of_evaluation_steps_per_iter=2,
            )

        # Every learner family on the default (batch-norm) backbone, plus
        # the layer-norm backbone variant whose norm/{weight,bias} leaves
        # keep the MP table's layer-norm rule live.
        families = [
            (cls, cls.__name__, cfg())
            for cls in (MAMLFewShotLearner, ANILLearner,
                        GradientDescentLearner, MatchingNetsLearner,
                        ProtoNetsLearner)
        ]
        families.append((
            MAMLFewShotLearner,
            "MAMLFewShotLearner[layer_norm]",
            cfg(norm_layer="layer_norm", per_step_bn_statistics=False),
        ))

        leaf_names: list[str] = []
        for cls, family, family_cfg in families:
            learner = cls(family_cfg)
            state = jax.eval_shape(
                learner.init_state, jax.random.PRNGKey(0)
            )
            paths, _ = tree_flatten_with_path(state)
            leaf_names.extend(
                f"{family}:{tree_path_name(path)}"
                for path, _leaf in paths
            )

        for table_name, rules in (
            ("DP_STATE_RULES", DP_STATE_RULES),
            ("MP_STATE_RULES", MP_STATE_RULES),
        ):
            used = [0] * len(rules)
            for name in leaf_names:
                _cls, _, path = name.partition(":")
                for index, (pattern, _spec) in enumerate(rules):
                    if _re.search(pattern, path) is not None:
                        used[index] += 1
                        break
                else:
                    yield self._table_violation(
                        table_name,
                        f"state leaf {name!r} matches no rule in "
                        f"{table_name} — it would raise at shard time "
                        "(replicate-by-omission is refused by design)",
                    )
            for index, (pattern, _spec) in enumerate(rules):
                if used[index] == 0:
                    yield self._table_violation(
                        pattern,
                        f"rule {pattern!r} in {table_name} matches no "
                        "state leaf of any learner family (first-match-"
                        "wins order) — a dead rule, delete it or fix "
                        "its pattern",
                    )


PROGRAM_RULES: list[ProgramRule] = [
    CollectiveBudgetRule(),
    DtypeLeakRule(),
    DonationViolationRule(),
    HostCallbackInStepRule(),
    SpecCoverageRule(),
]


def lint_programs(
    select: "set[str] | None" = None,
    analyses: "list[ProgramAnalysis] | None" = None,
) -> list[Violation]:
    """Traces the registered program table and runs every program rule.

    The whole pass is abstract — no device computation, no XLA compile
    (donation reads the pre-compile lowering). Trace failures surface as
    per-program findings through the rules that need the trace."""
    if analyses is None:
        analyses = analyze_registry()
    violations: list[Violation] = []
    for rule in PROGRAM_RULES:
        if select is not None and rule.id not in select:
            continue
        for analysis in analyses:
            violations.extend(rule.check_program(analysis))
        violations.extend(rule.check_registry(analyses))
    return sorted(
        violations, key=lambda v: (v.path, v.line, v.rule, v.message)
    )


def render_program_table(analyses: "list[ProgramAnalysis] | None" = None) -> str:
    """The ``--programs`` run's human-readable program table (README
    "Program lint" quickstart): one row per registered program with its
    per-meta-iteration collective count/bytes vs budget."""
    if analyses is None:
        analyses = analyze_registry()
    header = (
        f"{'program':<24} {'collectives/iter':>16} {'comm bytes':>11} "
        f"{'budget':>7} {'k':>3}  status"
    )
    rows = [header, "-" * len(header)]
    for analysis in analyses:
        spec = analysis.spec
        if analysis.error and analysis.aliased_outputs is None:
            status = f"TRACE ERROR: {analysis.error}"
            rows.append(f"{spec.name:<24} {'-':>16} {'-':>11} "
                        f"{spec.collective_budget:>7} {spec.k:>3}  {status}")
            continue
        status = (
            "over budget"
            if analysis.collective_count > spec.collective_budget
            else "ok"
        )
        rows.append(
            f"{spec.name:<24} {analysis.collective_count:>16} "
            f"{analysis.comm_bytes:>11} {spec.collective_budget:>7} "
            f"{spec.k:>3}  {status}"
        )
    return "\n".join(rows)


def ensure_cpu_devices(n: int = 8) -> None:
    """CLI bootstrap: the registry's mesh variants need multiple devices;
    force the virtual-CPU platform BEFORE jax initializes (no-op when a
    real multi-device backend is already configured)."""
    if os.environ.get("JAX_PLATFORMS", "cpu") != "cpu":
        return
    from howtotrainyourmamlpytorch_tpu.utils.platform import (
        force_virtual_cpu,
    )

    force_virtual_cpu(n)
