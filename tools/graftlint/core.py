"""Core datatypes: violations, parsed modules, the project container, and
inline-suppression parsing."""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field

from .tracing import TraceInfo, analyze_tracing, build_alias_map

#: Inline suppression comment syntax: hash, "graftlint:", "disable=" with a
#: comma-separated rule list, then " -- " and a mandatory reason (an
#: unreasoned or unknown-rule suppression is reported as bad-suppression).
_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*?))?\s*$"
)


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format_text(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: [{self.rule}] {self.message}"

    def format_github(self) -> str:
        return (
            f"::error file={self.path},line={self.line},col={self.col},"
            f"title=graftlint {self.rule}::{self.message}"
        )


@dataclass
class Suppression:
    line: int  # line the comment sits on
    rules: list[str]
    reason: str | None
    standalone: bool  # comment-only line -> also covers the next code line
    used: bool = False


@dataclass
class ModuleFile:
    """One parsed source file plus its lazily-computed analyses."""

    path: str
    source: str
    tree: ast.Module
    aliases: dict[str, str]
    suppressions: list[Suppression]
    _trace: TraceInfo | None = field(default=None, repr=False)

    @property
    def trace(self) -> TraceInfo:
        if self._trace is None:
            self._trace = analyze_tracing(self.tree, self.aliases)
        return self._trace

    @classmethod
    def parse(cls, path: str, source: str) -> "ModuleFile":
        tree = ast.parse(source, filename=path)
        return cls(
            path=path,
            source=source,
            tree=tree,
            aliases=build_alias_map(tree),
            suppressions=_parse_suppressions(source),
        )


@dataclass
class Project:
    """All modules under analysis (cross-file rules read the whole set)."""

    modules: list[ModuleFile]

    def by_basename(self, name: str) -> list[ModuleFile]:
        return [m for m in self.modules if m.path.endswith(name)]


def _parse_suppressions(source: str) -> list[Suppression]:
    out: list[Suppression] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            match = _SUPPRESS_RE.search(tok.string)
            if not match:
                continue
            rules = [r.strip() for r in match.group(1).split(",") if r.strip()]
            reason = match.group(2)
            standalone = tok.line.strip().startswith("#")
            out.append(
                Suppression(
                    line=tok.start[0],
                    rules=rules,
                    reason=reason if reason else None,
                    standalone=standalone,
                )
            )
    except tokenize.TokenError:
        pass
    return out


def apply_suppressions(
    module: ModuleFile, violations: list[Violation], known_rules: set[str]
) -> list[Violation]:
    """Drops violations covered by a well-formed inline suppression; emits
    ``bad-suppression`` for unreasoned or unknown-rule disables (those are
    not themselves suppressible — the enforcement would be circular)."""
    source_lines = module.source.splitlines()
    covered_lines: dict[int, list[Suppression]] = {}
    for sup in module.suppressions:
        covered_lines.setdefault(sup.line, []).append(sup)
        if sup.standalone:
            # A comment-only suppression covers the next CODE line — blank
            # lines and continuation comments (a multi-line reason) between
            # the marker and the code are skipped.
            for idx in range(sup.line, len(source_lines)):
                text = source_lines[idx].strip()
                if text and not text.startswith("#"):
                    covered_lines.setdefault(idx + 1, []).append(sup)
                    break

    kept: list[Violation] = []
    for v in violations:
        suppressed = False
        for sup in covered_lines.get(v.line, []):
            if v.rule in sup.rules and sup.reason:
                sup.used = True
                suppressed = True
        if not suppressed:
            kept.append(v)

    for sup in module.suppressions:
        malformed = False
        if not sup.reason:
            malformed = True
            kept.append(
                Violation(
                    rule="bad-suppression",
                    path=module.path,
                    line=sup.line,
                    col=0,
                    message=(
                        "suppression without a reason: write "
                        "'# graftlint: disable=<rule> -- <why this is safe>'"
                    ),
                )
            )
        for rule in sup.rules:
            if rule not in known_rules:
                malformed = True
                kept.append(
                    Violation(
                        rule="bad-suppression",
                        path=module.path,
                        line=sup.line,
                        col=0,
                        message=f"suppression names unknown rule {rule!r}",
                    )
                )
        # A well-formed suppression that silenced nothing is stale — the
        # code it excused was fixed or moved. Report it so disables are
        # cleaned up the moment they stop earning their keep.
        if not malformed and not sup.used:
            kept.append(
                Violation(
                    rule="bad-suppression",
                    path=module.path,
                    line=sup.line,
                    col=0,
                    message=(
                        "unused suppression: no "
                        f"{'/'.join(sup.rules)} violation on the covered "
                        "line — remove the disable comment"
                    ),
                )
            )
    return kept
