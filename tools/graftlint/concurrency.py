"""Whole-program concurrency & distributed-contract analysis (graftlint v2).

PRs 6-13 grew ~10k LoC of concurrent host-side control plane (replica-pool
supervisors, batcher workers, prefetch stagers, the async checkpoint
writer, watchdog monitors, promotion daemon threads), and every review
pass kept hand-finding the same failure classes: work done under a lock
that didn't need it, blocking calls inside critical sections, signal
handlers doing non-reentrant work, two ranks racing one tmp+rename, exit
codes invented ad hoc. This module makes those classes mechanical, the
way ``tracing.py`` already does for the JAX hot path:

* :class:`ConcurrencyAnalysis` builds one PROJECT-wide model per lint run
  (cached on the :class:`~tools.graftlint.core.Project`): which class /
  module attributes are ``threading.Lock``/``RLock``/``Condition`` objects
  (``Condition(self._lock)`` aliases the shared lock, so the prefetcher's
  two conditions are ONE lock, not three), which are queues/events, which
  ``self.x = SomeClass(...)`` attributes carry a project class (one-level
  type inference for ``self.engine.dispatch(...)``-style resolution), and
  a cross-module call graph covering relative imports (``from ..telemetry
  import events``) that :func:`~tools.graftlint.tracing.build_alias_map`
  deliberately skips.

* Every function is walked once with a held-lock stack: direct nested
  acquisitions yield lock-ORDER edges, call sites made with locks held
  are closed transitively over the call graph (bounded depth) so a lock
  acquired three helpers deep still produces its edge, and blocking
  primitives reachable with a lock held are reported at the call site
  that holds the lock.

The five rules riding the model are registered in ``rules.ALL_RULES``:
``lock-order-inversion``, ``blocking-under-lock``,
``signal-handler-unsafe``, ``chief-only-write`` and
``exit-code-contract``. The runtime twin is
``howtotrainyourmamlpytorch_tpu/utils/locksan.py`` — the instrumented-lock
sanitizer that records the ACTUAL acquisition-order graph during the
serve/chaos suites and is cross-validated against the static pass on the
same seeded deadlock (``tests/test_graftlint_concurrency.py``).

Everything here is heuristic by design (the tracing.py tradeoff):
zero-dependency, zero-execution, false-positive-averse first — tier-1
enforces a clean tree, so a noisy rule would be worse than no rule.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Iterator

from .core import ModuleFile, Project
from .tracing import resolve_dotted

#: Bounded interprocedural closure depth — deep enough for the real
#: chains in this tree (pool.promote -> checkpoint_digest -> open), small
#: enough that a pathological call graph cannot blow the lint run up.
MAX_CALL_DEPTH = 6

_LOCK_CTORS = {"threading.Lock", "threading.RLock"}
_CONDITION_CTOR = "threading.Condition"
_QUEUE_CTORS = {
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue",
}
_EVENT_CTOR = "threading.Event"

#: Calls that block the calling thread, by fully-resolved dotted path.
#: Keyed to the classes this codebase actually contains (HTTP scrapes,
#: subprocess waits, file hashing/copies, fsync, device syncs).
BLOCKING_CALLS = {
    "time.sleep": "time.sleep",
    "os.fsync": "os.fsync (durable-write barrier)",
    "urllib.request.urlopen": "HTTP request (urllib.request.urlopen)",
    "subprocess.run": "subprocess.run",
    "subprocess.call": "subprocess.call",
    "subprocess.check_call": "subprocess.check_call",
    "subprocess.check_output": "subprocess.check_output",
    "shutil.copyfile": "file copy (shutil.copyfile)",
    "shutil.copy": "file copy (shutil.copy)",
    "shutil.copytree": "tree copy (shutil.copytree)",
    "shutil.rmtree": "tree delete (shutil.rmtree)",
    "socket.create_connection": "socket connect",
    "requests.get": "HTTP request (requests.get)",
    "requests.post": "HTTP request (requests.post)",
    "jax.block_until_ready": "device sync (jax.block_until_ready)",
    "jax.device_get": "device fetch (jax.device_get)",
    "open": "file open for I/O",
}

#: Attribute-call tails that block regardless of receiver resolution.
#: ``communicate``/``wait_output`` only ever mean Popen here; ``join`` is
#: filtered through the same non-thread heuristics as thread-lifecycle.
_BLOCKING_TAILS = {
    "communicate": "subprocess communicate",
}

#: Method tails that dispatch jitted device programs in this codebase —
#: "jitted-step dispatch" from the issue: holding a host lock across a
#: device dispatch serializes every other thread behind device time.
_DISPATCH_TAILS = {"dispatch", "run_train_iter", "run_train_iters"}

#: Exit codes this repo has DECLARED (README "Fault tolerance" matrix is
#: regenerated from here; ``tests/test_graftlint_concurrency.py`` pins the
#: registry against the live constants so the two can never diverge).
#: Any other integer literal in ``sys.exit``/``os._exit``/``SystemExit``
#: is an undeclared exit code — name it here (with a meaning) or use a
#: declared constant.
EXIT_CODE_REGISTRY = {
    0: "success",
    1: "failure (generic; graftlint CLI findings)",
    2: "usage error (argparse; loadtest SLO FAIL)",
    3: "episode miner: nothing cleared the margin gate (no manifest)",
    75: "preemption requeue (EX_TEMPFAIL; resume on the same mesh)",
    76: "watchdog hang — requeue degraded (suspect the topology)",
    77: "device OOM (RESOURCE_EXHAUSTED) — forensics in logs/"
        "oom_report.json; do NOT requeue the same config",
    86: "serve replica fault-kill (injected worker death)",
}


# ---------------------------------------------------------------------------
# Import / call-target resolution (absolute + relative)
# ---------------------------------------------------------------------------


def _norm(path: str) -> str:
    return path.replace("\\", "/")


@dataclass
class _ClassInfo:
    module: "ModuleFile"
    node: ast.ClassDef
    methods: dict = field(default_factory=dict)  # name -> FunctionDef
    lock_attrs: dict = field(default_factory=dict)  # attr -> lock id
    queue_attrs: set = field(default_factory=set)
    event_attrs: set = field(default_factory=set)
    #: attr -> (module_path, class_name): one-level type inference from
    #: ``self.attr = SomeProjectClass(...)`` assignments.
    obj_attrs: dict = field(default_factory=dict)


@dataclass
class _FuncEntry:
    key: tuple  # (module_path, class_name|None, func_name)
    module: "ModuleFile"
    cls: _ClassInfo | None
    node: ast.AST
    #: lock ids acquired directly in this function.
    acquires: set = field(default_factory=set)
    #: (held_lock_id, acquired_lock_id, site_node) for direct nesting.
    edges: list = field(default_factory=list)
    #: (held frozenset, call node, resolved target key | None, label)
    calls: list = field(default_factory=list)
    #: (node, description, held frozenset) blocking primitives hit
    #: directly in this function (held may be empty — callers holding a
    #: lock still make them findings at the call site).
    blocking: list = field(default_factory=list)


class ConcurrencyAnalysis:
    """One project-wide pass shared by the concurrency/contract rules."""

    @classmethod
    def of(cls, project: Project) -> "ConcurrencyAnalysis":
        cached = getattr(project, "_concurrency_analysis", None)
        if cached is None:
            cached = cls(project)
            project._concurrency_analysis = cached
        return cached

    def __init__(self, project: Project):
        self.project = project
        self.modules = {m.path: m for m in project.modules}
        self._module_by_relpath: dict[str, ModuleFile] = {}
        for m in project.modules:
            self._module_by_relpath[_norm(m.path)] = m
        #: local name -> ("module"|"func"|"class", ModuleFile, name|None)
        self.imports: dict[str, dict] = {}
        self.classes: dict[tuple, _ClassInfo] = {}  # (path, name)
        self.module_locks: dict[str, dict] = {}  # path -> {name: lock id}
        self.funcs: dict[tuple, _FuncEntry] = {}
        self._acq_memo: dict[tuple, frozenset] = {}
        self._block_memo: dict[tuple, dict] = {}

        for m in project.modules:
            self.imports[m.path] = self._bind_imports(m)
        for m in project.modules:
            self._collect_classes(m)
        for m in project.modules:
            self._collect_module_locks(m)
        for m in project.modules:
            self._walk_functions(m)
        self._global_edges: list[dict] | None = None

    # -- imports --------------------------------------------------------

    def _find_module(self, dotted_or_parts: str) -> ModuleFile | None:
        """Project module for a dotted path, by path-suffix match."""
        rel = dotted_or_parts.replace(".", "/")
        for suffix in (f"{rel}.py", f"{rel}/__init__.py"):
            for path, module in self._module_by_relpath.items():
                if path == suffix or path.endswith("/" + suffix):
                    return module
        return None

    def _bind_imports(self, module: ModuleFile) -> dict:
        """Maps this module's local names to project targets, covering the
        relative imports ``build_alias_map`` skips."""
        binds: dict[str, dict] = {}
        base_dir = _norm(module.path).rsplit("/", 1)[0] if "/" in _norm(
            module.path
        ) else ""

        def bind_name(local: str, target: ModuleFile | None, attr: str | None):
            if target is None:
                return
            if attr is None:
                binds[local] = {"kind": "module", "module": target}
                return
            kind = None
            for node in target.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node.name == attr:
                        kind = "func"
                elif isinstance(node, ast.ClassDef) and node.name == attr:
                    kind = "class"
            if kind:
                binds[local] = {"kind": kind, "module": target, "name": attr}

        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    target = self._find_module(a.name)
                    if target is not None:
                        binds[a.asname or a.name.split(".")[0]] = {
                            "kind": "module", "module": target,
                        }
            elif isinstance(node, ast.ImportFrom):
                if node.level == 0:
                    src = node.module or ""
                    src_mod = self._find_module(src) if src else None
                    for a in node.names:
                        sub = (
                            self._find_module(f"{src}.{a.name}") if src else None
                        )
                        if sub is not None:
                            bind_name(a.asname or a.name, sub, None)
                        elif src_mod is not None:
                            bind_name(a.asname or a.name, src_mod, a.name)
                else:
                    parts = base_dir.split("/") if base_dir else []
                    up = node.level - 1
                    anchor = parts[: len(parts) - up] if up else parts
                    prefix = "/".join(
                        anchor + (node.module or "").split(".")
                    ).strip("/")
                    src_mod = self._find_module(prefix.replace("/", "."))
                    for a in node.names:
                        sub = self._find_module(
                            f"{prefix}/{a.name}".replace("/", ".")
                        )
                        if sub is not None:
                            bind_name(a.asname or a.name, sub, None)
                        elif src_mod is not None:
                            bind_name(a.asname or a.name, src_mod, a.name)
        return binds

    # -- class / lock discovery ----------------------------------------

    @staticmethod
    def _module_base(module: ModuleFile) -> str:
        name = _norm(module.path).rsplit("/", 1)[-1]
        return name[:-3] if name.endswith(".py") else name

    def _ctor_path(self, call: ast.Call, module: ModuleFile) -> str | None:
        return resolve_dotted(call.func, module.aliases)

    def _collect_classes(self, module: ModuleFile) -> None:
        base = self._module_base(module)
        for node in module.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            info = _ClassInfo(module=module, node=node)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    info.methods[item.name] = item
            # Attribute classification, in source order so a Condition
            # sharing an earlier lock aliases it.
            for meth in info.methods.values():
                for stmt in ast.walk(meth):
                    if not isinstance(stmt, ast.Assign) or not isinstance(
                        stmt.value, ast.Call
                    ):
                        continue
                    target = stmt.targets[0]
                    if not (
                        isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"
                    ):
                        continue
                    attr = target.attr
                    ctor = self._ctor_path(stmt.value, module)
                    if ctor in _LOCK_CTORS:
                        info.lock_attrs[attr] = f"{base}:{node.name}.{attr}"
                    elif ctor == _CONDITION_CTOR:
                        shared = None
                        if stmt.value.args:
                            arg = stmt.value.args[0]
                            if (
                                isinstance(arg, ast.Attribute)
                                and isinstance(arg.value, ast.Name)
                                and arg.value.id == "self"
                            ):
                                shared = info.lock_attrs.get(arg.attr)
                        info.lock_attrs[attr] = (
                            shared or f"{base}:{node.name}.{attr}"
                        )
                    elif ctor in _QUEUE_CTORS:
                        info.queue_attrs.add(attr)
                    elif ctor == _EVENT_CTOR:
                        info.event_attrs.add(attr)
                    elif ctor is not None:
                        resolved = self._resolve_class_ctor(ctor, module)
                        if resolved is not None:
                            info.obj_attrs[attr] = resolved
            self.classes[(module.path, node.name)] = info

    def _resolve_class_ctor(
        self, ctor: str, module: ModuleFile
    ) -> tuple | None:
        """``SomeClass`` / ``alias.SomeClass`` -> (module_path, class)."""
        head, _, tail = ctor.partition(".")
        binds = self.imports.get(module.path, {})
        if not tail:
            if (module.path, head) in self.classes or any(
                isinstance(n, ast.ClassDef) and n.name == head
                for n in module.tree.body
            ):
                return (module.path, head)
            bound = binds.get(head)
            if bound and bound["kind"] == "class":
                return (bound["module"].path, bound["name"])
            return None
        bound = binds.get(head)
        if bound and bound["kind"] == "module" and "." not in tail:
            target = bound["module"]
            if any(
                isinstance(n, ast.ClassDef) and n.name == tail
                for n in target.tree.body
            ):
                return (target.path, tail)
        return None

    def _collect_module_locks(self, module: ModuleFile) -> None:
        base = self._module_base(module)
        locks: dict[str, str] = {}
        for node in module.tree.body:
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                target = node.targets[0]
                if isinstance(target, ast.Name):
                    ctor = self._ctor_path(node.value, module)
                    if ctor in _LOCK_CTORS or ctor == _CONDITION_CTOR:
                        locks[target.id] = f"{base}:{target.id}"
        self.module_locks[module.path] = locks

    # -- lock expression resolution ------------------------------------

    def _lock_id_of(
        self, expr: ast.AST, module: ModuleFile, cls: _ClassInfo | None
    ) -> str | None:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            return cls.lock_attrs.get(expr.attr)
        if isinstance(expr, ast.Name):
            return self.module_locks.get(module.path, {}).get(expr.id)
        return None

    # -- call-target resolution ----------------------------------------

    def resolve_call(
        self, call: ast.Call, module: ModuleFile, cls: _ClassInfo | None
    ) -> tuple | None:
        """Call -> function key ``(module_path, class|None, name)`` when
        the target is resolvable inside the scanned project."""
        func = call.func
        binds = self.imports.get(module.path, {})
        if isinstance(func, ast.Name):
            bound = binds.get(func.id)
            if bound is not None:
                if bound["kind"] == "func":
                    return (bound["module"].path, None, bound["name"])
                if bound["kind"] == "class":
                    return (bound["module"].path, bound["name"], "__init__")
            for node in module.tree.body:
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node.name == func.id:
                        return (module.path, None, func.id)
                elif isinstance(node, ast.ClassDef) and node.name == func.id:
                    return (module.path, func.id, "__init__")
            return None
        if not isinstance(func, ast.Attribute):
            return None
        owner = func.value
        # self.m(...)
        if isinstance(owner, ast.Name) and owner.id == "self" and cls is not None:
            if func.attr in cls.methods:
                return (cls.module.path, cls.node.name, func.attr)
            return None
        # alias.m(...) where alias is a project module
        if isinstance(owner, ast.Name):
            bound = binds.get(owner.id)
            if bound is not None and bound["kind"] == "module":
                target = bound["module"]
                for node in target.tree.body:
                    if isinstance(
                        node, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ) and node.name == func.attr:
                        return (target.path, None, func.attr)
                    if (
                        isinstance(node, ast.ClassDef)
                        and node.name == func.attr
                    ):
                        return (target.path, func.attr, "__init__")
            return None
        # self.obj.m(...) via one-level attribute typing
        if (
            isinstance(owner, ast.Attribute)
            and isinstance(owner.value, ast.Name)
            and owner.value.id == "self"
            and cls is not None
        ):
            typed = cls.obj_attrs.get(owner.attr)
            if typed is not None:
                target_cls = self.classes.get(typed)
                if target_cls is not None and func.attr in target_cls.methods:
                    return (typed[0], typed[1], func.attr)
        return None

    # -- blocking-primitive classification -----------------------------

    def _blocking_desc(
        self, call: ast.Call, module: ModuleFile, cls: _ClassInfo | None,
        held: frozenset,
    ) -> str | None:
        resolved = resolve_dotted(call.func, module.aliases)
        if resolved in BLOCKING_CALLS:
            if resolved == "open" and not _opens_for_real(call):
                return None
            return BLOCKING_CALLS[resolved]
        func = call.func
        if not isinstance(func, ast.Attribute):
            return None
        tail = func.attr
        if tail in _BLOCKING_TAILS:
            return _BLOCKING_TAILS[tail]
        owner = func.value
        owner_attr = (
            owner.attr
            if isinstance(owner, ast.Attribute)
            and isinstance(owner.value, ast.Name)
            and owner.value.id == "self"
            else None
        )
        # Blocking queue get/put on a tracked queue attribute (unless
        # explicitly non-blocking).
        if tail in ("get", "put") and cls is not None and owner_attr is not None:
            if owner_attr in cls.queue_attrs and not _nonblocking_kwargs(call):
                return f"blocking queue.{tail} on self.{owner_attr}"
        # Future.result: only when the receiver visibly smells like one.
        if tail == "result":
            base = owner_attr or (owner.id if isinstance(owner, ast.Name) else "")
            if base and ("future" in base.lower() or base.lower().startswith("fut")):
                return f"Future.result on {base!r}"
        # Condition/Event wait: waiting on the HELD condition releases it
        # (that is what conditions are for); waiting on anything else
        # while a lock is held parks the lock across the wait.
        if tail in ("wait", "wait_for"):
            lock_id = self._lock_id_of(owner, module, cls)
            if lock_id is not None:
                return (
                    None if lock_id in held
                    else f"Condition.{tail} on a DIFFERENT lock ({lock_id})"
                )
            if cls is not None and owner_attr in cls.event_attrs:
                return f"Event.wait on self.{owner_attr}"
            return None
        if tail in _DISPATCH_TAILS:
            target = self.resolve_call(call, module, cls)
            if target is not None or tail == "dispatch":
                return f"jitted-step dispatch ({tail})"
        if tail == "join" and _is_thread_join_like(call, module):
            return "thread join"
        return None

    # -- per-function walk ---------------------------------------------

    def _walk_functions(self, module: ModuleFile) -> None:
        for node in module.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_one(module, None, node)
            elif isinstance(node, ast.ClassDef):
                info = self.classes[(module.path, node.name)]
                for meth in info.methods.values():
                    self._walk_one(module, info, meth)

    def _walk_one(
        self, module: ModuleFile, cls: _ClassInfo | None, fn: ast.AST
    ) -> None:
        key = (module.path, cls.node.name if cls else None, fn.name)
        entry = _FuncEntry(key=key, module=module, cls=cls, node=fn)
        self.funcs[key] = entry
        self._walk_stmts(list(fn.body), (), entry)

    def _walk_stmts(self, stmts: list, held: tuple, entry: _FuncEntry) -> None:
        held_list = list(held)
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # nested scope — its own walk would lose `self`
            if isinstance(stmt, ast.With):
                new = []
                for item in stmt.items:
                    lock_id = self._lock_id_of(
                        item.context_expr, entry.module, entry.cls
                    )
                    if lock_id is not None:
                        entry.acquires.add(lock_id)
                        for h in held_list + new:
                            if h != lock_id:
                                entry.edges.append((h, lock_id, stmt))
                        new.append(lock_id)
                    else:
                        self._scan_exprs(item.context_expr, held_list, entry)
                self._walk_stmts(
                    stmt.body, tuple(held_list + new), entry
                )
                continue
            # Explicit acquire()/release() on a tracked lock.
            if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
                call = stmt.value
                if isinstance(call.func, ast.Attribute) and call.func.attr in (
                    "acquire", "release",
                ):
                    lock_id = self._lock_id_of(
                        call.func.value, entry.module, entry.cls
                    )
                    if lock_id is not None:
                        if call.func.attr == "acquire":
                            entry.acquires.add(lock_id)
                            for h in held_list:
                                if h != lock_id:
                                    entry.edges.append((h, lock_id, stmt))
                            held_list.append(lock_id)
                        elif lock_id in held_list:
                            held_list.remove(lock_id)
                        continue
            for child_body in _stmt_bodies(stmt):
                self._walk_stmts(child_body, tuple(held_list), entry)
            for expr in _stmt_exprs(stmt):
                self._scan_exprs(expr, held_list, entry)

    def _scan_exprs(self, expr: ast.AST, held_list: list, entry: _FuncEntry):
        held = frozenset(held_list)
        for node in ast.walk(expr):
            if isinstance(node, (ast.Lambda,)):
                continue
            if not isinstance(node, ast.Call):
                continue
            desc = self._blocking_desc(node, entry.module, entry.cls, held)
            if desc is not None:
                entry.blocking.append((node, desc, held))
                continue
            target = self.resolve_call(node, entry.module, entry.cls)
            if target is not None and target != entry.key:
                label = _call_label(node)
                entry.calls.append((held, node, target, label))

    # -- transitive summaries ------------------------------------------

    def acq_closure(self, key: tuple, _depth: int = 0, _stack=None) -> frozenset:
        """Locks a function may acquire, including via project callees."""
        if key in self._acq_memo:
            return self._acq_memo[key]
        entry = self.funcs.get(key)
        if entry is None:
            return frozenset()
        stack = _stack or set()
        if key in stack or _depth > MAX_CALL_DEPTH:
            return frozenset(entry.acquires)
        stack = stack | {key}
        out = set(entry.acquires)
        for _held, _node, target, _label in entry.calls:
            if target is not None:
                out |= self.acq_closure(target, _depth + 1, stack)
        result = frozenset(out)
        if _depth == 0:
            self._acq_memo[key] = result
        return result

    def block_closure(self, key: tuple, _depth: int = 0, _stack=None) -> dict:
        """Blocking primitives reachable from a function: desc -> chain."""
        if key in self._block_memo:
            return self._block_memo[key]
        entry = self.funcs.get(key)
        if entry is None:
            return {}
        stack = _stack or set()
        if key in stack or _depth > MAX_CALL_DEPTH:
            return {}
        stack = stack | {key}
        out: dict[str, str] = {}
        for _node, desc, _held in entry.blocking:
            out.setdefault(desc, _key_label(key))
        for _held, _node, target, label in entry.calls:
            if target is None:
                continue
            for desc, chain in self.block_closure(
                target, _depth + 1, stack
            ).items():
                out.setdefault(desc, f"{_key_label(key)} -> {chain}")
        if _depth == 0:
            self._block_memo[key] = out
        return out

    # -- the global lock-order graph -----------------------------------

    def lock_order_edges(self) -> list[dict]:
        """Every (held -> acquired) edge in the project: direct nestings
        plus lock-held call sites closed over the callee's acquisition
        set. Each edge remembers its site for reporting/suppression."""
        if self._global_edges is not None:
            return self._global_edges
        edges: list[dict] = []
        for key, entry in self.funcs.items():
            for held_id, acq_id, node in entry.edges:
                edges.append({
                    "src": held_id, "dst": acq_id,
                    "module": entry.module, "node": node,
                    "via": f"direct nesting in {_key_label(key)}",
                })
            for held, node, target, label in entry.calls:
                if not held or target is None:
                    continue
                for acq_id in self.acq_closure(target):
                    for held_id in held:
                        if held_id != acq_id:
                            edges.append({
                                "src": held_id, "dst": acq_id,
                                "module": entry.module, "node": node,
                                "via": (
                                    f"call to {label} (which acquires "
                                    f"{acq_id}) in {_key_label(key)}"
                                ),
                            })
        self._global_edges = edges
        return edges

    def lock_order_cycles(self) -> tuple[set, list[dict]]:
        """(set of lock-ids inside some cycle, the edges between them).
        Tarjan SCC (components of size >= 2 are cyclic orders) shared
        with the runtime sanitizer via ``utils/algo.tarjan_scc`` — the
        package ``__init__`` is import-free, so graftlint stays
        importable without jax."""
        from howtotrainyourmamlpytorch_tpu.utils.algo import tarjan_scc

        edges = self.lock_order_edges()
        adj: dict[str, set] = {}
        for e in edges:
            adj.setdefault(e["src"], set()).add(e["dst"])
        cyclic: set[str] = set()
        for component in tarjan_scc(adj):
            cyclic.update(component)
        cycle_edges = [
            e for e in edges if e["src"] in cyclic and e["dst"] in cyclic
        ]
        return cyclic, cycle_edges


# -- small AST helpers ------------------------------------------------------


def _stmt_bodies(stmt: ast.stmt) -> Iterator[list]:
    for attr in ("body", "orelse", "finalbody"):
        body = getattr(stmt, attr, None)
        if isinstance(body, list) and body and isinstance(body[0], ast.stmt):
            yield body
    for handler in getattr(stmt, "handlers", []) or []:
        yield handler.body


def _stmt_exprs(stmt: ast.stmt) -> Iterator[ast.AST]:
    """Expression children of a statement, excluding nested statement
    bodies (those are walked with their own held-stack)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
        return
    for name, value in ast.iter_fields(stmt):
        if name in ("body", "orelse", "finalbody", "handlers"):
            continue
        if isinstance(value, ast.AST):
            yield value
        elif isinstance(value, list):
            for item in value:
                if isinstance(item, ast.AST) and not isinstance(item, ast.stmt):
                    yield item


def _nonblocking_kwargs(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "block" and isinstance(kw.value, ast.Constant):
            if kw.value.value is False:
                return True
        if kw.arg == "timeout" and isinstance(kw.value, ast.Constant):
            if kw.value.value == 0:
                return True
    return False


def _opens_for_real(call: ast.Call) -> bool:
    """``open`` blocks on real I/O either way; reading tiny configs under
    a lock is still a finding, so every ``open`` counts."""
    return True


def _is_thread_join_like(call: ast.Call, module: ModuleFile) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute):
        return False
    if isinstance(func.value, ast.Constant):
        return False  # ", ".join(...)
    resolved = resolve_dotted(func, module.aliases) or ""
    return not resolved.startswith(
        ("os.path.", "posixpath.", "ntpath.", "str.")
    )


def _call_label(call: ast.Call) -> str:
    try:
        return ast.unparse(call.func)
    except Exception:  # pragma: no cover - unparse of exotic nodes
        return "<call>"


def _key_label(key: tuple) -> str:
    path, cls, name = key
    base = _norm(path).rsplit("/", 1)[-1]
    return f"{base}:{cls}.{name}" if cls else f"{base}:{name}"


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

from .rules import Rule  # noqa: E402  (cycle-free: rules imports nothing back)


class LockOrderInversionRule(Rule):
    id = "lock-order-inversion"
    summary = (
        "two locks are acquired in opposite orders on different code "
        "paths (interprocedural, project-wide) — a potential deadlock the "
        "chaos harness can only ever catch probabilistically"
    )

    def check(self, module, project):
        analysis = ConcurrencyAnalysis.of(project)
        _cyclic, cycle_edges = analysis.lock_order_cycles()
        seen: set[tuple] = set()
        for edge in cycle_edges:
            if edge["module"] is not module:
                continue
            pos = (edge["node"].lineno, edge["src"], edge["dst"])
            if pos in seen:
                continue
            seen.add(pos)
            yield self._v(
                module,
                edge["node"],
                f"acquiring {edge['dst']!r} while holding {edge['src']!r} "
                f"({edge['via']}) participates in a cyclic lock order — "
                "another path acquires these locks in the opposite order; "
                "pick one global order or narrow one critical section",
            )


class BlockingUnderLockRule(Rule):
    id = "blocking-under-lock"
    summary = (
        "a blocking call (queue get/put, Future.result, HTTP, subprocess, "
        "fsync, file I/O, sleep, foreign Condition.wait, jitted dispatch) "
        "runs or is reachable while a threading lock is held — every "
        "other thread serializes behind the slow operation"
    )

    def check(self, module, project):
        analysis = ConcurrencyAnalysis.of(project)
        seen: set[tuple] = set()
        for key, entry in analysis.funcs.items():
            if entry.module is not module:
                continue
            for node, desc, held in entry.blocking:
                if not held:
                    continue
                pos = (node.lineno, node.col_offset)
                if pos in seen:
                    continue
                seen.add(pos)
                yield self._v(
                    module,
                    node,
                    f"{desc} while holding {sorted(held)[0]!r} — move the "
                    "blocking work outside the critical section",
                )
            for held, node, target, label in entry.calls:
                if not held or target is None:
                    continue
                blocked = analysis.block_closure(target)
                if not blocked:
                    continue
                pos = (node.lineno, node.col_offset)
                if pos in seen:
                    continue
                seen.add(pos)
                desc, chain = sorted(blocked.items())[0]
                yield self._v(
                    module,
                    node,
                    f"call to {label} reaches {desc} (via {chain}) while "
                    f"holding {sorted(held)[0]!r} — move the call outside "
                    "the critical section or split the helper",
                )


#: Calls a signal handler may make. Python handlers run on the MAIN
#: thread between bytecodes: acquiring a lock the interrupted code holds
#: deadlocks instantly, and buffered-I/O ``print`` can die with
#: "RuntimeError: reentrant call" when the signal lands mid-print. The
#: sanctioned moves: set a flag, ``os.write`` (unbuffered), raise, wake an
#: Event, or hand the real work to a fresh thread.
_HANDLER_SAFE_CALLS = {
    "os.write", "os.kill", "os._exit", "signal.raise_signal",
}


class SignalHandlerUnsafeRule(Rule):
    id = "signal-handler-unsafe"
    summary = (
        "a signal handler does more than set a flag / os.write / raise / "
        "Event.set / spawn a thread — locks, blocking calls and buffered "
        "I/O (print) in a handler deadlock or die reentrantly when the "
        "signal lands at the wrong bytecode"
    )

    def _handler_target(self, call, module, analysis):
        """The handler callable of a ``signal.signal(sig, handler)`` call:
        a FunctionDef/Lambda node plus its class context, or None when the
        handler is not statically resolvable (restore loops passing a
        saved variable are deliberately skipped)."""
        if len(call.args) < 2:
            return None
        handler = call.args[1]
        if isinstance(handler, ast.Lambda):
            return handler, None
        if isinstance(handler, ast.Name):
            for node in ast.walk(module.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if node.name == handler.id:
                        return node, None
        if (
            isinstance(handler, ast.Attribute)
            and isinstance(handler.value, ast.Name)
            and handler.value.id == "self"
        ):
            for (path, cls_name), info in analysis.classes.items():
                if path == module.path and handler.attr in info.methods:
                    return info.methods[handler.attr], info
        return None

    def _enclosing_class(self, module, target_node):
        for node in module.tree.body:
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if sub is target_node:
                        return node.name
        return None

    def check(self, module, project):
        analysis = ConcurrencyAnalysis.of(project)
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if resolve_dotted(node.func, module.aliases) != "signal.signal":
                continue
            resolved = self._handler_target(node, module, analysis)
            if resolved is None:
                continue
            handler, cls = resolved
            if cls is None:
                # Lambdas and nested defs inherit the enclosing class's
                # ``self`` (the SIGUSR1 idiom: ``lambda s, f:
                # self.profiler.request(...)`` inside a method).
                cls_name = self._enclosing_class(module, handler)
                if cls_name is not None:
                    cls = analysis.classes.get((module.path, cls_name))
            yield from self._check_handler(
                module, analysis, handler, cls, depth=0
            )

    def _check_handler(self, module, analysis, handler, cls, depth):
        body = (
            handler.body
            if isinstance(handler, (ast.FunctionDef, ast.AsyncFunctionDef))
            else [ast.Expr(value=handler.body)]
        )
        for stmt in body:
            yield from self._check_stmt(module, analysis, stmt, cls, depth)

    def _check_stmt(self, module, analysis, stmt, cls, depth):
        if isinstance(stmt, ast.With):
            yield self._v(
                module, stmt,
                "with-statement (lock/resource acquisition) inside a "
                "signal handler — if the signal lands while the main "
                "thread holds the same lock, the handler deadlocks the "
                "process; set a flag instead",
            )
            return
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            verdict = self._classify_call(module, analysis, node, cls, depth)
            if verdict is not None:
                yield self._v(module, node, verdict)

    def _classify_call(self, module, analysis, call, cls, depth):
        resolved = resolve_dotted(call.func, module.aliases)
        if resolved in _HANDLER_SAFE_CALLS:
            return None
        if resolved in ("threading.Thread", "Thread"):
            return None  # ctor of the defer-to-thread pattern (see start)
        if resolved in ("str", "int", "float", "bytes", "repr", "len"):
            return None  # pure in-memory conversion
        if isinstance(call.func, ast.Attribute) and call.func.attr in (
            "encode", "decode", "format",
        ):
            return None  # string shaping for an os.write payload
        if resolved == "print":
            return (
                "print() inside a signal handler — buffered writers are "
                "not reentrant (a signal landing mid-print raises "
                "RuntimeError and crashes the run); use os.write on the "
                "raw fd after setting the flag"
            )
        func = call.func
        if isinstance(func, ast.Attribute):
            if func.attr == "acquire" or (
                cls is not None
                and isinstance(func.value, ast.Attribute)
                and isinstance(func.value.value, ast.Name)
                and func.value.value.id == "self"
                and cls.lock_attrs.get(func.value.attr)
            ):
                return (
                    "lock operation inside a signal handler — deadlocks "
                    "when the signal interrupts a holder on this thread"
                )
            if func.attr == "set" and not call.args:
                return None  # Event.set — the wake-a-waiter idiom
            # threading.Thread(...).start(): the sanctioned defer-to-
            # thread pattern (the handler itself stays trivial).
            if func.attr == "start" and isinstance(func.value, ast.Call):
                ctor = resolve_dotted(func.value.func, module.aliases)
                if ctor in ("threading.Thread", "Thread"):
                    return None
        desc = analysis._blocking_desc(call, module, cls, frozenset())
        if desc is not None:
            return f"{desc} inside a signal handler — handlers must not block"
        target = analysis.resolve_call(call, module, cls)
        if target is not None:
            if depth >= 2:
                return (
                    f"call chain deeper than 2 from a signal handler "
                    f"({_call_label(call)}) — keep handlers to a flag set"
                )
            entry = analysis.funcs.get(target)
            if entry is not None:
                target_cls = entry.cls
                problems = list(
                    self._check_handler(
                        entry.module, analysis, entry.node, target_cls,
                        depth + 1,
                    )
                )
                if problems:
                    return (
                        f"call to {_call_label(call)} from a signal handler "
                        f"reaches unsafe work ({problems[0].message[:120]})"
                    )
                return None
        if isinstance(func, ast.Name) and func.id in (
            "KeyboardInterrupt", "SystemExit", "RuntimeError",
        ):
            return None  # exception construction inside a raise
        if resolved is not None and resolved.startswith(("os.", "signal.")):
            return None  # os/signal-namespace calls are the safe surface
        return (
            f"unverifiable call {_call_label(call)} inside a signal "
            "handler — handlers may only set flags, os.write, raise, wake "
            "an Event, or spawn a worker thread"
        )


class ChiefOnlyWriteRule(Rule):
    id = "chief-only-write"
    summary = (
        "a filesystem mutation in a chief-electing module (one that binds "
        "a rank-0 flag from process_index) is reachable on every rank — "
        "two ranks racing one tmp+rename corrupt the shared file"
    )

    #: Mutation primitives in scope (the tmp+rename class plus open-for-
    #: write). Reads and makedirs(exist_ok=True) are rank-safe.
    WRITE_CALLS = {"os.replace", "os.rename", "shutil.copyfile", "shutil.move"}
    WRITE_TAILS = {
        "save_checkpoint", "publish_alias", "publish_done_marker",
        "save_to_json", "save_statistics", "save_model", "save_models",
    }

    def _chief_names(self, module) -> set[str]:
        """Names bound as ``<x> = ... process_index ... == 0`` where the
        target smells like an election flag."""
        names: set[str] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Assign):
                continue
            target = node.targets[0]
            name = None
            if isinstance(target, ast.Name):
                name = target.id
            elif isinstance(target, ast.Attribute) and isinstance(
                target.value, ast.Name
            ):
                name = target.attr
            if name is None or "chief" not in name.lower():
                continue
            source = ast.dump(node.value)
            if "process_index" in source:
                names.add(name)
        return names

    def _is_write_call(self, call, module) -> str | None:
        resolved = resolve_dotted(call.func, module.aliases)
        if resolved in self.WRITE_CALLS:
            return resolved
        if resolved == "open" and len(call.args) >= 2:
            mode = call.args[1]
            if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
                if set(mode.value) & set("wax+"):
                    return f"open(..., {mode.value!r})"
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                if resolved == "open" and set(str(kw.value.value)) & set("wax+"):
                    return f"open(..., mode={kw.value.value!r})"
        func = call.func
        if isinstance(func, ast.Attribute) and func.attr in self.WRITE_TAILS:
            return func.attr
        if isinstance(func, ast.Name) and func.id in self.WRITE_TAILS:
            return func.id
        return None

    @staticmethod
    def _guard_hits(test: ast.AST, chief_names: set[str]) -> bool:
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and node.id in chief_names:
                return True
            if isinstance(node, ast.Attribute) and node.attr in chief_names:
                return True
        return False

    def _guard_line(self, fn, chief_names) -> int | None:
        """Line of the early-return election (``if not chief: return``)
        among the function's top-level statements — every statement after
        it runs chief-only. Statements BEFORE the guard (path computation,
        timers) are allowed as long as they are not themselves writes
        (the caller checks write line vs guard line)."""
        for stmt in fn.body:
            if isinstance(stmt, ast.If) and self._guard_hits(
                stmt.test, chief_names
            ):
                # The guard body may keep a little per-rank bookkeeping
                # (timer resets) as long as it EXITS: only the last
                # statement must be the return/raise.
                body_exits = bool(stmt.body) and isinstance(
                    stmt.body[-1], (ast.Return, ast.Raise)
                )
                negated = isinstance(stmt.test, ast.UnaryOp) and isinstance(
                    stmt.test.op, ast.Not
                )
                if negated and body_exits:
                    return stmt.lineno
        return None

    def _function_chief_safe(self, fn, chief_names) -> bool:
        return self._guard_line(fn, chief_names) is not None

    def check(self, module, project):
        chief_names = self._chief_names(module)
        if not chief_names:
            return
        # Pass 1: functions that only ever execute on the chief — either
        # via the early-return election or because EVERY call site in the
        # module sits under a positive chief guard / in a chief-only
        # function (fixpoint over the module-local call graph).
        functions: dict[str, ast.AST] = {}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                functions.setdefault(node.name, node)
        chief_only: set[str] = {
            name for name, fn in functions.items()
            if self._function_chief_safe(fn, chief_names)
        }
        # Parent map for "is this node under `if chief:`" checks.
        parents: dict[int, ast.AST] = {}
        for node in ast.walk(module.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node

        def under_positive_guard(node: ast.AST) -> bool:
            cur = node
            while cur is not None:
                parent = parents.get(id(cur))
                if isinstance(parent, ast.If) and cur in parent.body:
                    test = parent.test
                    negated = isinstance(test, ast.UnaryOp) and isinstance(
                        test.op, ast.Not
                    )
                    if self._guard_hits(test, chief_names) and not negated:
                        return True
                cur = parent
            return False

        def enclosing_function(node: ast.AST):
            cur = parents.get(id(node))
            while cur is not None and not isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                cur = parents.get(id(cur))
            return cur

        changed = True
        while changed:
            changed = False
            for name, fn in functions.items():
                if name in chief_only:
                    continue
                sites = []
                for node in ast.walk(module.tree):
                    if isinstance(node, ast.Call):
                        callee = None
                        if isinstance(node.func, ast.Name):
                            callee = node.func.id
                        elif isinstance(node.func, ast.Attribute) and isinstance(
                            node.func.value, ast.Name
                        ) and node.func.value.id in ("self", "cls"):
                            callee = node.func.attr
                        if callee == name:
                            sites.append(node)
                if not sites:
                    continue
                ok = True
                for site in sites:
                    enc = enclosing_function(site)
                    if under_positive_guard(site):
                        continue
                    if enc is not None and enc.name in chief_only and (
                        enc.name != name
                    ):
                        continue
                    ok = False
                    break
                if ok:
                    chief_only.add(name)
                    changed = True

        seen: set[tuple] = set()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            what = self._is_write_call(node, module)
            if what is None:
                continue
            enc = enclosing_function(node)
            if under_positive_guard(node):
                continue
            if enc is not None:
                guard = self._guard_line(enc, chief_names)
                if guard is not None and node.lineno > guard:
                    continue
                if enc.name in chief_only:
                    continue
            # A call to a module-local writer that itself opens with the
            # election (save_models guards internally) is already safe.
            callee = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif isinstance(node.func, ast.Attribute) and isinstance(
                node.func.value, ast.Name
            ) and node.func.value.id in ("self", "cls"):
                callee = node.func.attr
            if callee is not None and callee in functions and (
                self._guard_line(functions[callee], chief_names) is not None
            ):
                continue
            pos = (node.lineno, node.col_offset)
            if pos in seen:
                continue
            seen.add(pos)
            yield self._v(
                module,
                node,
                f"filesystem mutation ({what}) reachable on every rank of "
                "a chief-electing module — guard it with the rank-0 "
                "election (or suppress with a reason if the path is "
                "genuinely per-rank)",
            )


class ExitCodeContractRule(Rule):
    id = "exit-code-contract"
    summary = (
        "an undeclared integer exit code in sys.exit/os._exit/SystemExit "
        "(the registry lives in tools/graftlint/concurrency.py), or a "
        "bare `except:` swallowing everything at a typed boundary"
    )

    EXIT_FUNCS = {"sys.exit", "os._exit"}

    def check(self, module, project):
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                resolved = resolve_dotted(node.func, module.aliases)
                is_exit = resolved in self.EXIT_FUNCS or (
                    isinstance(node.func, ast.Name)
                    and node.func.id == "SystemExit"
                )
                if is_exit and node.args:
                    arg = node.args[0]
                    if isinstance(arg, ast.Constant) and isinstance(
                        arg.value, int
                    ) and not isinstance(arg.value, bool):
                        if arg.value not in EXIT_CODE_REGISTRY:
                            yield self._v(
                                module,
                                node,
                                f"undeclared process exit code {arg.value} "
                                "— add it to EXIT_CODE_REGISTRY (tools/"
                                "graftlint/concurrency.py) with a meaning, "
                                "or reuse a declared constant "
                                f"({sorted(EXIT_CODE_REGISTRY)})",
                            )
            elif isinstance(node, ast.ExceptHandler) and node.type is None:
                reraises = any(
                    isinstance(sub, ast.Raise) and sub.exc is None
                    for sub in ast.walk(node)
                )
                if not reraises:
                    yield self._v(
                        module,
                        node,
                        "bare `except:` swallows SystemExit/Keyboard"
                        "Interrupt at a typed-exception boundary — catch "
                        "Exception (or the typed error) instead, or "
                        "re-raise",
                    )


CONCURRENCY_RULES = [
    LockOrderInversionRule(),
    BlockingUnderLockRule(),
    SignalHandlerUnsafeRule(),
    ChiefOnlyWriteRule(),
    ExitCodeContractRule(),
]
