"""The graftlint rule set — the AST-level hazard detectors.

Every rule yields :class:`~tools.graftlint.core.Violation` objects and is
registered in :data:`ALL_RULES`. Rules are heuristics tuned against this
codebase: false-positive-averse first (tier-1 enforces a clean tree), and
each carries at least one positive and one negative unit test in
``tests/test_graftlint.py``.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Iterator

from .core import ModuleFile, Project, Violation
from .tracing import (
    is_device_call,
    iter_traced_functions,
    param_names,
    resolve_dotted,
    taint_names,
    unwrap_partial,
)


class Rule:
    id: str = ""
    summary: str = ""

    def check(self, module: ModuleFile, project: Project) -> Iterator[Violation]:
        raise NotImplementedError

    def _v(self, module: ModuleFile, node: ast.AST, message: str) -> Violation:
        return Violation(
            rule=self.id,
            path=module.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


def _scope_nodes(scope: ast.AST) -> Iterator[ast.AST]:
    """Nodes of one lexical scope, not descending into nested function
    bodies (those are their own scopes)."""
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _store_names(node: ast.AST) -> set[str]:
    return {
        n.id
        for n in ast.walk(node)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
    }


class PRNGReuseRule(Rule):
    id = "prng-reuse"
    summary = (
        "a PRNG key variable feeds two jax.random consumers (or one inside "
        "a loop) without jax.random.split — identical randomness, silently"
    )

    #: jax.random functions that do NOT consume the key's entropy budget.
    NONCONSUMING = {
        "split", "fold_in", "PRNGKey", "key", "wrap_key_data", "key_data",
        "clone", "key_impl",
    }

    def _consumer_key_arg(self, call: ast.Call, module: ModuleFile) -> str | None:
        resolved = resolve_dotted(call.func, module.aliases)
        if not resolved or not resolved.startswith("jax.random."):
            return None
        if resolved.rpartition(".")[2] in self.NONCONSUMING:
            return None
        if call.args and isinstance(call.args[0], ast.Name):
            return call.args[0].id
        return None

    def check(self, module, project):
        scopes: list[ast.AST] = [module.tree]
        scopes += [
            n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for scope in scopes:
            yield from self._check_scope(scope, module)

    def _check_scope(self, scope, module):
        events: list[tuple[int, int, str, str, ast.AST]] = []
        for node in _scope_nodes(scope):
            if isinstance(node, ast.Call):
                key = self._consumer_key_arg(node, module)
                if key is not None:
                    events.append((node.lineno, node.col_offset, "use", key, node))
            elif isinstance(node, (ast.Assign, ast.AugAssign, ast.For)):
                target = node.target if not isinstance(node, ast.Assign) else node
                for name in _store_names(target):
                    events.append(
                        (node.lineno, getattr(node, "col_offset", 0), "def", name, node)
                    )
        loops = [
            n for n in _scope_nodes(scope) if isinstance(n, (ast.For, ast.While))
        ]

        used: set[str] = set()
        for _, _, kind, name, node in sorted(events, key=lambda e: (e[0], e[1])):
            if kind == "def":
                used.discard(name)
                continue
            if name in used:
                yield self._v(
                    module,
                    node,
                    f"PRNG key {name!r} already consumed in this scope; "
                    "split it (jax.random.split) before reusing",
                )
            used.add(name)
            for loop in loops:
                if self._node_in(node, loop) and name not in _store_names(loop):
                    yield self._v(
                        module,
                        node,
                        f"PRNG key {name!r} consumed inside a loop without "
                        "re-splitting — every iteration draws identical "
                        "randomness",
                    )
                    break

    @staticmethod
    def _node_in(node: ast.AST, container: ast.AST) -> bool:
        return any(n is node for n in ast.walk(container))


class HostNumpyInTraceRule(Rule):
    id = "host-numpy-in-trace"
    summary = (
        "a host numpy call receives a traced/device value inside a "
        "jitted/scanned function — baked-constant or trace error"
    )

    def check(self, module, project):
        seen: set[tuple[int, int]] = set()
        for fn in iter_traced_functions(module.tree, module.trace):
            tainted = taint_names(fn, module.aliases, include_params=True)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                resolved = resolve_dotted(node.func, module.aliases)
                if not resolved or not resolved.startswith("numpy."):
                    continue
                args = list(node.args) + [kw.value for kw in node.keywords]
                if not any(self._tainted_expr(a, tainted, module) for a in args):
                    continue
                pos = (node.lineno, node.col_offset)
                if pos in seen:
                    continue
                seen.add(pos)
                yield self._v(
                    module,
                    node,
                    f"host-numpy call {resolved.replace('numpy.', 'np.', 1)!r} "
                    "on a traced value inside a traced function — use the "
                    "jnp equivalent",
                )

    @staticmethod
    def _tainted_expr(expr, tainted, module):
        for node in ast.walk(expr):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in tainted:
                    return True
            if isinstance(node, ast.Call) and is_device_call(node, module.aliases):
                return True
        return False


class TracerBranchRule(Rule):
    id = "tracer-branch"
    summary = (
        "Python if/while branches on a tracer-derived value inside a traced "
        "function — TracerBoolConversionError, or silently-static branch"
    )

    #: device-namespace calls whose results are static (shape metadata).
    STATIC_QUERY_TAILS = {"ndim", "shape", "size", "result_type", "issubdtype"}

    def check(self, module, project):
        seen: set[tuple[int, int]] = set()
        for fn in iter_traced_functions(module.tree, module.trace):
            tainted = taint_names(fn, module.aliases, include_params=False)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if not self._test_is_traced(node.test, tainted, module):
                    continue
                pos = (node.lineno, node.col_offset)
                if pos in seen:
                    continue
                seen.add(pos)
                kind = "if" if isinstance(node, ast.If) else "while"
                yield self._v(
                    module,
                    node,
                    f"Python `{kind}` on a tracer-derived value inside a "
                    "traced function — use lax.cond / lax.select / "
                    "lax.while_loop",
                )

    def _test_is_traced(self, test, tainted, module):
        for node in ast.walk(test):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                if node.id in tainted:
                    return True
            if isinstance(node, ast.Call) and is_device_call(node, module.aliases):
                resolved = resolve_dotted(node.func, module.aliases) or ""
                if resolved.rpartition(".")[2] not in self.STATIC_QUERY_TAILS:
                    return True
        return False


def _jit_sites(module: ModuleFile):
    """Yields ``(site_node, wrapped, static_kwnames, assign_name)`` for
    every ``jax.jit``/``pjit`` call site and decorator in the module.

    ``wrapped`` is the callable expression being jitted (the FunctionDef
    itself for decorator form); ``assign_name`` is the name the compiled
    function is bound to, when the site is the RHS of an assignment.
    """
    jit_tails = {"jit", "pjit"}

    def is_jit(node) -> bool:
        resolved = resolve_dotted(node, module.aliases)
        return bool(resolved) and resolved.rpartition(".")[2] in jit_tails and (
            resolved.startswith("jax") or resolved == "pjit"
        )

    assign_names: dict[int, str] = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            target = node.targets[0]
            name = None
            if isinstance(target, ast.Name):
                name = target.id
            elif isinstance(target, ast.Attribute):
                name = target.attr
            elif isinstance(target, ast.Subscript):
                name = (
                    target.value.attr
                    if isinstance(target.value, ast.Attribute)
                    else getattr(target.value, "id", None)
                )
            if name:
                assign_names[id(node.value)] = name

    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and is_jit(node.func):
            if not node.args:
                continue
            kwnames = {kw.arg for kw in node.keywords if kw.arg}
            yield node, node.args[0], kwnames, assign_names.get(id(node))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if is_jit(dec):
                    yield dec, node, set(), node.name
                elif isinstance(dec, ast.Call):
                    kwnames = {kw.arg for kw in dec.keywords if kw.arg}
                    if is_jit(dec.func):
                        yield dec, node, kwnames, node.name
                    elif (
                        resolve_dotted(dec.func, module.aliases)
                        in ("functools.partial", "partial")
                        and dec.args
                        and is_jit(dec.args[0])
                    ):
                        yield dec, node, kwnames, node.name


def _wrapped_params(wrapped: ast.AST, module: ModuleFile):
    """Parameter names of the callable being jitted, or None when the
    callable is defined elsewhere. Returns ``(params, was_partial)``."""
    if isinstance(wrapped, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
        return param_names(wrapped), False
    inner, was_partial = unwrap_partial(wrapped, module.aliases)
    if isinstance(inner, ast.Lambda):
        return param_names(inner), was_partial
    name = None
    if isinstance(inner, ast.Name):
        name = inner.id
    elif isinstance(inner, ast.Attribute) and isinstance(inner.value, ast.Name):
        if inner.value.id in ("self", "cls"):
            name = inner.attr
    if name:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == name:
                    return param_names(node), was_partial
    return None, was_partial


def _wrapped_name(wrapped: ast.AST, module: ModuleFile) -> str | None:
    if isinstance(wrapped, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return wrapped.name
    inner, _ = unwrap_partial(wrapped, module.aliases)
    if isinstance(inner, ast.Name):
        return inner.id
    if isinstance(inner, ast.Attribute):
        return inner.attr
    return None


class JitStaticConfigRule(Rule):
    id = "jit-static-config"
    summary = (
        "a jit/pjit site whose wrapped function takes a config-shaped "
        "argument without static_argnames — retrace/recompile hazard"
    )

    CONFIG_NAMES = {
        "config", "cfg", "flags", "opts", "options", "hparams", "settings",
        "hps", "mode",
    }
    CONFIG_SUFFIXES = ("_config", "_cfg", "_flags", "_opts", "_options")

    def _is_config_param(self, name: str) -> bool:
        return name in self.CONFIG_NAMES or name.endswith(self.CONFIG_SUFFIXES)

    def check(self, module, project):
        for site, wrapped, kwnames, _assign in _jit_sites(module):
            if kwnames & {"static_argnames", "static_argnums"}:
                continue
            params, was_partial = _wrapped_params(wrapped, module)
            if params is None or was_partial:
                # partial() binds its config at wrap time — static by
                # construction; unresolvable callables are skipped.
                continue
            config_params = [p for p in params if self._is_config_param(p)]
            if config_params:
                yield self._v(
                    module,
                    site,
                    f"jit of a function taking config-shaped argument(s) "
                    f"{config_params} without static_argnames — every "
                    "config change retraces silently, and unhashable "
                    "configs retrace per call",
                )


class MissingDonateRule(Rule):
    id = "missing-donate"
    summary = (
        "a train-step-shaped jit (threads a state pytree through an update) "
        "without donate_argnums — doubles peak device memory"
    )

    STATE_PARAMS = {"state", "train_state", "carry", "opt_state", "learner_state"}
    TRAIN_RE = re.compile(r"train|update")
    EXEMPT_RE = re.compile(r"eval|valid|test|predict|infer|loss|lower|apply")

    def check(self, module, project):
        for site, wrapped, kwnames, assign_name in _jit_sites(module):
            if kwnames & {"donate_argnums", "donate_argnames"}:
                continue
            candidates = [
                n for n in (_wrapped_name(wrapped, module), assign_name) if n
            ]
            if not candidates:
                continue
            if any(self.EXEMPT_RE.search(n) for n in candidates):
                continue
            if not any(self.TRAIN_RE.search(n) for n in candidates):
                continue
            params, _ = _wrapped_params(wrapped, module)
            if not params or params[0] not in self.STATE_PARAMS:
                continue
            yield self._v(
                module,
                site,
                f"train-step jit of {candidates[0]!r} threads state param "
                f"{params[0]!r} without donate_argnums — the old state "
                "buffer stays live across the update (2x peak memory)",
            )


class DeadFlagRule(Rule):
    id = "dead-flag"
    summary = (
        "a CLI flag defined in utils/parser_utils.py that no scanned module "
        "reads — config surface rot (needs a full-tree scan to fire)"
    )

    #: Minimum distinct modules with flag reads before the scan is trusted
    #: as complete enough to call anything dead (see the guard below).
    MIN_READING_MODULES = 4

    def check(self, module, project):
        if not module.path.endswith("parser_utils.py"):
            return
        flags: list[tuple[str, ast.Call]] = []
        defining_fns: list[ast.AST] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            is_add = (isinstance(node.func, ast.Name) and node.func.id == "add") or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_argument"
            )
            if not is_add or not node.args:
                continue
            first = node.args[0]
            if isinstance(first, ast.Constant) and isinstance(first.value, str):
                if first.value.startswith("--"):
                    flags.append((first.value.lstrip("-"), node))
        if not flags:
            return
        flag_lines = {id(call) for _, call in flags}
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(
                    isinstance(sub, ast.Call) and id(sub) in flag_lines
                    for sub in ast.walk(node)
                ):
                    defining_fns.append(node)

        reads: set[str] = set()
        reading_modules: set[str] = set()
        names = {name for name, _ in flags}
        for mod in project.modules:
            skip_nodes: set[int] = set()
            if mod is module:
                for fn in defining_fns:
                    skip_nodes.update(id(n) for n in ast.walk(fn))
            for node in ast.walk(mod.tree):
                if id(node) in skip_nodes:
                    continue
                hit = None
                if isinstance(node, ast.Attribute) and node.attr in names:
                    hit = node.attr
                elif isinstance(node, ast.keyword) and node.arg in names:
                    hit = node.arg
                elif isinstance(node, ast.Constant) and isinstance(node.value, str):
                    if node.value in names:
                        hit = node.value
                if hit is not None:
                    reads.add(hit)
                    reading_modules.add(mod.path)
        # Partial-scan guard: "dead" is relative to the scanned file set.
        # Linting parser_utils.py alone (or any changed-files subset) would
        # report every flag whose consumers weren't scanned — a wall of
        # false positives. Flag consumers span the whole tree (models/,
        # data/, experiment runtime, entry points, tests), so the rule only
        # trusts a scan in which reads come from several distinct modules;
        # the tier-1 gate always scans the full tree, which is where the
        # rule enforces.
        if len(reading_modules) < self.MIN_READING_MODULES:
            return
        for name, call in flags:
            if name not in reads:
                yield self._v(
                    module,
                    call,
                    f"flag --{name} is defined but never read by any scanned "
                    "module — delete it or wire it to a consumer",
                )


class DeviceOpInDataPathRule(Rule):
    id = "device-op-in-data-path"
    summary = (
        "jax/jnp imported in the host-side data path — episode synthesis "
        "must stay on host numpy (device transfers belong to the step)"
    )

    # Every module under a data/ package directory is in scope — a new
    # data/ module importing jax is flagged the day it lands, not when
    # someone remembers to extend a file list.
    HOST_DATA_DIR = "/data/"

    # The ONE sanctioned exception: the device-prefetch stager exists
    # precisely to issue ``jax.device_put`` from the data path (staging
    # batches onto the device ahead of dispatch is its whole job, and the
    # put is async — no forced read). Allowlisted here rather than via an
    # inline suppression so the data-path ban stays zero-suppression and
    # the exception is auditable in one place.
    ALLOWED_FILES = ("data/device_prefetch.py",)

    def check(self, module, project):
        path = module.path.replace("\\", "/")
        if self.HOST_DATA_DIR not in f"/{path}":
            return
        if path.endswith(self.ALLOWED_FILES):
            return
        for node in ast.walk(module.tree):
            modname = None
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax" or a.name.startswith("jax."):
                        modname = a.name
            elif isinstance(node, ast.ImportFrom) and node.level == 0:
                if node.module and (
                    node.module == "jax" or node.module.startswith("jax.")
                ):
                    modname = node.module
            if modname:
                yield self._v(
                    module,
                    node,
                    f"{modname!r} imported in the host data path — jnp ops "
                    "here force host->device transfers per episode; keep "
                    "synthesis in numpy and decode on device in the step",
                )


class TracedMutationRule(Rule):
    id = "traced-mutation"
    summary = (
        "captured Python state mutated inside a traced function — runs once "
        "at trace time, then never again (silent staleness)"
    )

    MUTATORS = {
        "append", "extend", "insert", "setdefault", "remove", "discard",
        "clear", "popitem",
    }

    def check(self, module, project):
        seen: set[tuple[int, int]] = set()
        for fn in iter_traced_functions(module.tree, module.trace):
            local = set(param_names(fn)) | {
                n.id
                for n in ast.walk(fn)
                if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store)
            }
            for node in ast.walk(fn):
                v = self._check_node(node, local, module, fn)
                if v is not None:
                    pos = (v.line, v.col)
                    if pos not in seen:
                        seen.add(pos)
                        yield v

    def _base_name(self, node: ast.AST) -> str | None:
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return node.id if isinstance(node, ast.Name) else None

    def _check_node(self, node, local, module, fn):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            kind = "global" if isinstance(node, ast.Global) else "nonlocal"
            return self._v(
                module,
                node,
                f"`{kind} {', '.join(node.names)}` write inside a traced "
                "function — executes at trace time only; thread the value "
                "through the carry/return instead",
            )
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    base = self._base_name(t)
                    if base == "self" or (
                        base is not None
                        and base not in local
                        and base not in module.aliases
                    ):
                        return self._v(
                            module,
                            node,
                            f"mutation of captured object {base!r} inside a "
                            "traced function — happens once at trace time, "
                            "not per step; return the value instead",
                        )
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in self.MUTATORS:
                base = self._base_name(node.func.value)
                if base == "self" or (
                    base is not None
                    and base not in local
                    and base not in module.aliases
                ):
                    return self._v(
                        module,
                        node,
                        f".{node.func.attr}() on captured object {base!r} "
                        "inside a traced function — mutates at trace time "
                        "only; accumulate via scan/carry instead",
                    )
        return None


class ThreadLifecycleRule(Rule):
    id = "thread-lifecycle"
    summary = (
        "a retained threading.Thread is spawned with no join path "
        "reachable from its owner — shutdown/rollback/exit paths leak "
        "the thread (and whatever it pins)"
    )

    # Attribute tails whose .join() is NOT a thread join.
    NON_THREAD_JOIN_PREFIXES = ("os.path.", "posixpath.", "ntpath.", "str.")

    def _is_thread_ctor(self, call: ast.Call, module: ModuleFile) -> bool:
        resolved = resolve_dotted(call.func, module.aliases)
        return resolved in ("threading.Thread", "Thread") or (
            bool(resolved) and resolved.endswith(".Thread")
            and "threading" in resolved
        )

    def _is_thread_join(self, call: ast.Call, module: ModuleFile) -> bool:
        func = call.func
        if not (isinstance(func, ast.Attribute) and func.attr == "join"):
            return False
        # ", ".join(parts) — a string-literal base is never a thread.
        if isinstance(func.value, ast.Constant):
            return False
        resolved = resolve_dotted(func, module.aliases) or ""
        return not resolved.startswith(self.NON_THREAD_JOIN_PREFIXES)

    @staticmethod
    def _retained(call: ast.Call, parents: dict) -> bool:
        """Whether the ctor's result is stored somewhere a later join
        could reach (assignment / comprehension / collection). A pure
        fire-and-forget expression (``Thread(...).start()``) has no
        joinable handle — flagging it would only force pointless
        renames, so it is out of scope."""
        node = call
        while node is not None:
            parent = parents.get(id(node))
            if isinstance(parent, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                   ast.NamedExpr, ast.Return)):
                return True
            if isinstance(parent, (ast.ListComp, ast.SetComp, ast.DictComp,
                                   ast.GeneratorExp, ast.List, ast.Tuple,
                                   ast.Dict, ast.keyword)):
                return True
            if isinstance(parent, ast.Expr):
                return False
            node = parent
        return False

    def check(self, module, project):
        parents: dict[int, ast.AST] = {}
        enclosing_class: dict[int, ast.ClassDef | None] = {}

        def walk(node, cls):
            if isinstance(node, ast.ClassDef):
                cls = node
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
                enclosing_class[id(child)] = cls
                walk(child, cls)

        walk(module.tree, None)

        spawns = []
        module_has_join = False
        class_joins: set[int] = set()  # ids of classes with a join method
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            if self._is_thread_ctor(node, module):
                if self._retained(node, parents):
                    spawns.append(node)
            elif self._is_thread_join(node, module):
                module_has_join = True
                cls = enclosing_class.get(id(node))
                if cls is not None:
                    class_joins.add(id(cls))

        for spawn in spawns:
            cls = enclosing_class.get(id(spawn))
            if cls is not None:
                # Spawned by a class: the join must live in a method of
                # that same class (the owner's close/shutdown path) — a
                # join elsewhere in the module cannot reach this
                # instance's thread handle.
                if id(cls) in class_joins:
                    continue
                yield self._v(
                    module,
                    spawn,
                    f"class {cls.name!r} spawns a threading.Thread but no "
                    "method of it ever joins one — register a close/"
                    "shutdown path that joins the thread (see "
                    "DevicePrefetcher.close / DispatchWatchdog.close)",
                )
            elif not module_has_join:
                yield self._v(
                    module,
                    spawn,
                    "module spawns a retained threading.Thread but never "
                    "joins any thread — the owner's shutdown path cannot "
                    "reclaim it",
                )


class DeviceProbeBeforeDistributedInitRule(Rule):
    id = "device-probe-before-distributed-init"
    summary = (
        "jax.devices()/jax.local_devices() probed before "
        "initialize_distributed in a multi-host entry point — the probe "
        "initializes the XLA backend, after which the runtime can never "
        "span hosts (utils/platform.py documents the ordering)"
    )

    #: jax calls that initialize the backend (after which
    #: jax.distributed.initialize cannot take effect for this process).
    PROBES = {
        "jax.devices",
        "jax.local_devices",
        "jax.device_count",
        "jax.local_device_count",
    }
    INIT_NAMES = (
        "initialize_distributed",
        "initialize_distributed_from_argv",
    )

    def _imports_init(self, module: ModuleFile) -> bool:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ImportFrom):
                if any(a.name in self.INIT_NAMES for a in node.names):
                    return True
        return False

    def _is_init_call(self, call: ast.Call, module: ModuleFile) -> bool:
        resolved = resolve_dotted(call.func, module.aliases) or ""
        return resolved.rpartition(".")[2] in self.INIT_NAMES

    def _is_probe_call(self, call: ast.Call, module: ModuleFile) -> bool:
        resolved = resolve_dotted(call.func, module.aliases) or ""
        return resolved in self.PROBES

    def check(self, module, project):
        # Scope: only modules that IMPORT the bring-up helper — exactly
        # the entry points whose ordering the contract constrains. A
        # module with no multi-host ambition may probe devices freely.
        if not self._imports_init(module):
            return
        scopes: list[ast.AST] = [module.tree] + [
            n
            for n in ast.walk(module.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        module_has_init = any(
            isinstance(n, ast.Call) and self._is_init_call(n, module)
            for n in ast.walk(module.tree)
        )
        for scope in scopes:
            init_lines = []
            probes = []
            for node in _scope_nodes(scope):
                if not isinstance(node, ast.Call):
                    continue
                if self._is_init_call(node, module):
                    init_lines.append(node.lineno)
                elif self._is_probe_call(node, module):
                    probes.append(node)
            first_init = min(init_lines) if init_lines else None
            for probe in probes:
                if first_init is not None and probe.lineno < first_init:
                    yield self._v(
                        module,
                        probe,
                        "device probe before initialize_distributed — the "
                        "probe initializes the XLA backend, so the later "
                        "bring-up call can never make this process join a "
                        "multi-host runtime; call initialize_distributed "
                        "first (utils/platform.py documents the ordering)",
                    )
                elif first_init is None and not module_has_init and (
                    scope is module.tree
                ):
                    yield self._v(
                        module,
                        probe,
                        "module-level device probe in a file that imports "
                        "initialize_distributed but never calls it — the "
                        "probe pins this process single-host before any "
                        "bring-up can run",
                    )


class DurableWriteRule(Rule):
    id = "durable-write"
    summary = (
        "a journal/spill/durable-tier path is opened with a truncating "
        "mode via bare open() — a crash mid-write leaves a torn file "
        "where the durability contract promises old-or-new; route the "
        "write through serve/tier/atomic.atomic_write_bytes "
        "(tmp + fsync + rename)"
    )

    #: Substrings of the path-argument SOURCE that mark a durable
    #: artifact tree-wide. Deliberately narrow ("journal", "spill" — not
    #: "logs"): debug/log sinks are rewrite-on-start by design and a
    #: torn log line costs nothing, while a torn journal or spill entry
    #: silently corrupts recovery state.
    DURABLE_MARKERS = ("journal", "spill")

    #: Inside ``serve/tier/`` every truncating open is a violation
    #: regardless of variable naming — except the atomic helper itself,
    #: which is the one sanctioned writer.
    TIER_FRAGMENT = "serve/tier/"
    TIER_EXEMPT_BASENAME = "atomic.py"

    @staticmethod
    def _mode_literal(call: ast.Call) -> str | None:
        """The call's mode if it is a string literal (positional #2 or
        ``mode=``); None when absent or dynamic — a computed mode is out
        of scope for a false-positive-averse rule."""
        for kw in call.keywords:
            if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                    and isinstance(kw.value.value, str):
                return kw.value.value
        if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant) \
                and isinstance(call.args[1].value, str):
            return call.args[1].value
        return None

    def check(self, module, project):
        norm = module.path.replace("\\", "/")
        in_tier = (
            self.TIER_FRAGMENT in norm
            and os.path.basename(norm) != self.TIER_EXEMPT_BASENAME
        )
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            # Builtin open() only: Path.open()/os.open() carry different
            # semantics and naming them would multiply false positives.
            if not (isinstance(node.func, ast.Name) and node.func.id == "open"):
                continue
            mode = self._mode_literal(node)
            if mode is None or "w" not in mode:
                continue  # default "r", appends, and dynamic modes pass
            if in_tier:
                yield self._v(
                    module,
                    node,
                    f"truncating open(..., {mode!r}) inside serve/tier/ — "
                    "every durable-tier write must go through "
                    "atomic_write_bytes (tmp + fsync + rename) so a crash "
                    "leaves old-or-new, never a torn file",
                )
                continue
            path_arg = node.args[0] if node.args else None
            if path_arg is None:
                for kw in node.keywords:
                    if kw.arg == "file":
                        path_arg = kw.value
                        break
            if path_arg is None:
                continue
            try:
                src = ast.unparse(path_arg).lower()
            except Exception:
                continue
            if any(marker in src for marker in self.DURABLE_MARKERS):
                yield self._v(
                    module,
                    node,
                    f"truncating open(..., {mode!r}) on a path naming a "
                    "journal/spill artifact — durable state must be "
                    "written via atomic_write_bytes (tmp + fsync + "
                    "rename) or appended, never rewritten in place",
                )


ALL_RULES: list[Rule] = [
    PRNGReuseRule(),
    HostNumpyInTraceRule(),
    TracerBranchRule(),
    JitStaticConfigRule(),
    MissingDonateRule(),
    DeadFlagRule(),
    DeviceOpInDataPathRule(),
    TracedMutationRule(),
    ThreadLifecycleRule(),
    DeviceProbeBeforeDistributedInitRule(),
    DurableWriteRule(),
]

# The whole-program concurrency/contract rules (graftlint v2) live in
# their own module around the shared project-wide lock model; imported
# at the bottom so `Rule` exists when concurrency.py imports it back.
from .concurrency import CONCURRENCY_RULES  # noqa: E402

ALL_RULES.extend(CONCURRENCY_RULES)

# The IR-level program contract rules (graftlint v3) trace registered
# programs through jax.make_jaxpr instead of reading source; their AST
# hook is a no-op so they ride --list-rules/--select/README sync, and
# they fire through `python -m tools.graftlint --programs`.
from .programs import PROGRAM_RULES  # noqa: E402

ALL_RULES.extend(PROGRAM_RULES)
