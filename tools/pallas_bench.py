"""Measures the Pallas fused bn+leaky_relu kernel stack on every consumer
path (VERDICT r2 weak #5 / r3 next #10, extended for the second-order train
stack):

* the MAML++ eval path (custom_vjp kernel pair — the 1.28x r3 number) and
  the GD / matching-nets TRAINING paths (single outer grad, same op);
* the MAML++ TRAIN path — second order, reverse-over-reverse — through the
  second-order-capable ``fused_bn_leaky_relu_ho`` op
  (``--fused_norm_train``), at both the flagship Omniglot shapes and the
  mini-ImageNet north-star shapes (84x84x3, 48 filters, max-pool blocks,
  batch 2, 5-shot/15-target), with and without the fused max-pool epilogue
  (``--fused_norm_pool``).

Usage: python tools/pallas_bench.py [--skip-imagenet]
(quiet chip; prints one line per case plus speedup summaries)
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402


def _timed(step, drain, budget_s=6.0):
    step()  # compile
    drain()
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        step()
        n += 1
    drain()
    return n / (time.perf_counter() - t0)


def _with_backbone(cfg, **kwargs):
    return dataclasses.replace(
        cfg, backbone=dataclasses.replace(cfg.backbone, **kwargs)
    )


def _measure_train(results, key, cfg, batch, budget_s=6.0):
    """Second-order K=1 train-step rate for one config variant."""
    from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner

    learner = MAMLFewShotLearner(cfg)
    box = [learner.init_state(jax.random.PRNGKey(3))]

    def step():
        # epoch 20: steady state — second order, past the MSL horizon.
        box[0], _ = learner.run_train_iter(box[0], batch, epoch=20)

    results[key] = _timed(
        step, lambda: jax.block_until_ready(box[0].theta), budget_s
    )


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--skip-imagenet", action="store_true",
        help="skip the (slow) mini-ImageNet-shape train cases",
    )
    args = parser.parse_args()

    from __graft_entry__ import _episode_batch, _flagship_config
    from howtotrainyourmamlpytorch_tpu.models import (
        GradientDescentLearner,
        MAMLFewShotLearner,
        MatchingNetsLearner,
    )
    from howtotrainyourmamlpytorch_tpu.models.common import WireCodec

    results = {}

    # ------------------------------------------------------------------
    # One-level-AD consumers (custom_vjp kernel pair): eval + baselines
    # ------------------------------------------------------------------
    for fused in (False, True):
        cfg = dataclasses.replace(
            _flagship_config(), wire_codec=WireCodec(1.0, None, None)
        )
        cfg = _with_backbone(cfg, use_pallas_fused_norm=fused)
        rng = np.random.RandomState(0)
        batch = _episode_batch(8, cfg, rng)

        # MAML++ eval path (runs fused when enabled: one-level AD).
        learner = MAMLFewShotLearner(cfg)
        state = learner.init_state(jax.random.PRNGKey(0))
        out = [None]

        def eval_step():
            out[0] = learner.run_validation_iter(state, batch)[1]["loss"]

        rate = _timed(eval_step, lambda: jax.block_until_ready(out[0]))
        results[f"maml_eval_fused={fused}"] = rate

        # GD training (single value_and_grad per task -> fused eligible).
        gd = GradientDescentLearner(cfg)
        gd_state_box = [gd.init_state(jax.random.PRNGKey(1))]

        def gd_step():
            gd_state_box[0], _ = gd.run_train_iter(
                gd_state_box[0], batch, epoch=0
            )

        rate = _timed(
            gd_step, lambda: jax.block_until_ready(gd_state_box[0].theta)
        )
        results[f"gd_train_fused={fused}"] = rate

        # Matching-nets training.
        mn = MatchingNetsLearner(cfg)
        mn_state_box = [mn.init_state(jax.random.PRNGKey(2))]

        def mn_step():
            mn_state_box[0], _ = mn.run_train_iter(
                mn_state_box[0], batch, epoch=0
            )

        rate = _timed(
            mn_step, lambda: jax.block_until_ready(mn_state_box[0].theta)
        )
        results[f"mn_train_fused={fused}"] = rate

    # ------------------------------------------------------------------
    # Second-order MAML TRAIN path (custom_jvp ho op): flagship shapes
    # ------------------------------------------------------------------
    base = dataclasses.replace(
        _flagship_config(), wire_codec=WireCodec(1.0, None, None)
    )
    rng = np.random.RandomState(1)
    batch = _episode_batch(8, base, rng)
    _measure_train(results, "maml_train2_fused=off", base, batch)
    _measure_train(
        results, "maml_train2_fused=jvp",
        _with_backbone(base, fused_norm_train=True), batch,
    )

    # ------------------------------------------------------------------
    # Second-order MAML TRAIN path: mini-ImageNet north-star shapes
    # (the ~3.8% MFU regime the fused train stack targets — PERF_NOTES.md)
    # ------------------------------------------------------------------
    if not args.skip_imagenet:
        from bench import _imagenet_shape_config

        im = dataclasses.replace(
            _imagenet_shape_config(), wire_codec=WireCodec(255.0, None, None)
        )
        rng = np.random.RandomState(2)
        im_batch = _episode_batch(2, im, rng, shots=5, targets_per_class=15)
        _measure_train(
            results, "imagenet_train2_fused=off", im, im_batch, budget_s=20.0
        )
        _measure_train(
            results, "imagenet_train2_fused=jvp",
            _with_backbone(im, fused_norm_train=True), im_batch,
            budget_s=20.0,
        )
        _measure_train(
            results, "imagenet_train2_fused=jvp+pool",
            _with_backbone(im, fused_norm_train=True, fused_norm_pool=True),
            im_batch, budget_s=20.0,
        )

    for key, rate in results.items():
        print(f"{key}: {rate:.1f} iters/s")
    for name in ("maml_eval", "gd_train", "mn_train"):
        off = results[f"{name}_fused=False"]
        on = results[f"{name}_fused=True"]
        print(f"{name} fused speedup: {on / off:.3f}x")
    for name in ("maml_train2", "imagenet_train2"):
        if f"{name}_fused=off" not in results:
            continue
        off = results[f"{name}_fused=off"]
        for variant in ("jvp", "jvp+pool"):
            if f"{name}_fused={variant}" in results:
                on = results[f"{name}_fused={variant}"]
                print(f"{name} fused[{variant}] speedup: {on / off:.3f}x")


if __name__ == "__main__":
    main()
