"""Measures the Pallas fused bn+leaky_relu kernel on its remaining consumers
(VERDICT r2 weak #5 / next #10): the MAML++ eval path (the 1.12x number from
r2), the ensemble-test-eval shape (600 tasks / batch 8), and the GD and
matching-nets TRAINING paths (single outer grad — the one-level-AD regime
the kernel supports).

Usage: python tools/pallas_bench.py   (quiet chip; prints one line per case)
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402


def _timed(step, drain, budget_s=6.0):
    step()  # compile
    drain()
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < budget_s:
        step()
        n += 1
    drain()
    return n / (time.perf_counter() - t0)


def main() -> None:
    from __graft_entry__ import _episode_batch, _flagship_config
    from howtotrainyourmamlpytorch_tpu.models import (
        GradientDescentLearner,
        MAMLFewShotLearner,
        MatchingNetsLearner,
    )
    from howtotrainyourmamlpytorch_tpu.models.common import WireCodec

    results = {}
    for fused in (False, True):
        cfg = dataclasses.replace(
            _flagship_config(), wire_codec=WireCodec(1.0, None, None)
        )
        cfg = dataclasses.replace(
            cfg,
            backbone=dataclasses.replace(
                cfg.backbone, use_pallas_fused_norm=fused
            ),
        )
        rng = np.random.RandomState(0)
        batch = _episode_batch(8, cfg, rng)

        # MAML++ eval path (runs fused when enabled: one-level AD).
        learner = MAMLFewShotLearner(cfg)
        state = learner.init_state(jax.random.PRNGKey(0))
        out = [None]

        def eval_step():
            out[0] = learner.run_validation_iter(state, batch)[1]["loss"]

        rate = _timed(eval_step, lambda: jax.block_until_ready(out[0]))
        results[f"maml_eval_fused={fused}"] = rate

        # GD training (single value_and_grad per task -> fused eligible).
        gd = GradientDescentLearner(cfg)
        gd_state_box = [gd.init_state(jax.random.PRNGKey(1))]

        def gd_step():
            gd_state_box[0], _ = gd.run_train_iter(
                gd_state_box[0], batch, epoch=0
            )

        rate = _timed(
            gd_step, lambda: jax.block_until_ready(gd_state_box[0].theta)
        )
        results[f"gd_train_fused={fused}"] = rate

        # Matching-nets training.
        mn = MatchingNetsLearner(cfg)
        mn_state_box = [mn.init_state(jax.random.PRNGKey(2))]

        def mn_step():
            mn_state_box[0], _ = mn.run_train_iter(
                mn_state_box[0], batch, epoch=0
            )

        rate = _timed(
            mn_step, lambda: jax.block_until_ready(mn_state_box[0].theta)
        )
        results[f"mn_train_fused={fused}"] = rate

    for key, rate in results.items():
        print(f"{key}: {rate:.1f} iters/s")
    for name in ("maml_eval", "gd_train", "mn_train"):
        off = results[f"{name}_fused=False"]
        on = results[f"{name}_fused=True"]
        print(f"{name} fused speedup: {on / off:.3f}x")


if __name__ == "__main__":
    main()
