"""Serving-path benchmark: adapt+classify throughput and latency keys.

Measures the in-process serving runtime (``ServingAPI`` — engine + batcher
+ cache; HTTP excluded by design so the keys track DEVICE-path regressions,
not json parsing) at the flagship Omniglot shapes, and prints ONE JSON line
with the PERF_NOTES.md "Serving path" keys:

* ``serve_qps``            — cold-support episodes/s through the batched
                             adapt+classify pipeline (every episode pays
                             the inner loop), offered concurrently so
                             micro-batching engages;
* ``serve_adapt_p50_ms`` / ``serve_adapt_p99_ms`` — adapt dispatch latency
                             quantiles over the run (per meta-batch);
* ``serve_classify_p50_ms``                       — same for classify;
* ``serve_cache_hit_qps``  — episodes/s when every support set is already
                             cached (the adapted-params cache's best case:
                             classify-only);
* ``serve_compiles``       — compile-table size + total traces at exit
                             (the zero-per-request-recompile receipt);
* ``telemetry_overhead_pct`` — hot-path cost of the structured event sink
                             (``telemetry/events.py``): cache-hit qps with
                             a sink installed vs without, back-to-back;
* ``serve_error_rate`` / ``serve_shed_total`` / ``serve_deadline_exceeded_total``
                           — failures observed during the offered phases:
                             an overloaded bench run reports its sheds and
                             timeouts instead of healthy-looking qps;
* ``serve_slo_p99_ms`` / ``serve_loadtest_p99_ms`` / ``serve_loadtest_error_rate``
  / ``serve_recovery_s``   — the resilience receipt: an open-loop Poisson
                             loadtest (``tools/serve_loadtest.py``) against
                             a 2-replica in-process pool with a replica
                             kill injected mid-stream; recovery is the
                             measured death-to-full-health window;
* ``protonets_serve_qps`` / ``anil_adapt_p50_ms`` — the learner-zoo keys:
                             cold episodes/s through the protonets metric
                             tier (adapt = embed + class mean) and the
                             p50 dispatch latency of ANIL's head-only
                             inner loop, same pipeline and synthesis as
                             the MAML keys;
* ``geometry_mix_compiles`` — total program traces after a mixed
                             ``--geometry-mix`` stream through a declared
                             ``--geometry-lattice`` engine: must hold at
                             adapt+classify per bucket (heterogeneous
                             traffic mints no programs);
* ``serve_cold_ready_s`` / ``serve_replica_ready_s`` / ``serve_tier_hit_qps``
                           — the durable-tier receipt: first build on a
                             fresh tier dir (real compiles + adapts) vs a
                             respawn on the SAME dir (AOT executables
                             deserialized, artifacts rehydrated), and the
                             episodes/s served entirely from the verified
                             disk spill (RAM cache capacity forced to 0).

Usage: ``python tools/serve_bench.py [--tiny] [--budget-s 5]
[--skip-loadtest]``
(``--tiny`` runs a 2-stage 14x14 net — CI-sized; default is the flagship
64-filter 28x28 Omniglot config on the current backend, quiet-chip protocol
per PERF_NOTES.md).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def parse_geometries(spec: str) -> list[tuple[int, int, int]]:
    """``"5x1x15,3x2x8"`` -> ``[(5, 1, 15), (3, 2, 8)]`` — the CLI spelling
    of a geometry mix / lattice (shared with tools/serve_loadtest.py)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        dims = tuple(int(d) for d in part.split("x"))
        if len(dims) != 3:
            raise ValueError(
                f"geometry {part!r} must be WxSxQ (e.g. 5x1x15)"
            )
        out.append(dims)
    if not out:
        raise ValueError(f"no geometries in {spec!r}")
    return out


def build_api(
    tiny: bool,
    max_batch: int,
    max_wait_ms: float,
    cache: int,
    tier_dir: str | None = None,
    family: str = "maml",
    geometry_lattice=None,
):
    import jax

    from howtotrainyourmamlpytorch_tpu.models import (
        ANILLearner,
        BackboneConfig,
        MAMLConfig,
        MAMLFewShotLearner,
        ProtoNetsLearner,
    )
    from howtotrainyourmamlpytorch_tpu.serve import ServeConfig, ServingAPI

    # Geometry coarsening's bit-exactness contract requires a
    # row-independent forward (serve/geometry.py): a lattice flips the
    # backbone to layer norm; everything else benches the flagship's
    # per-step batch-norm shapes.
    norm = {"norm_layer": "layer_norm"} if geometry_lattice else {}
    if tiny:
        cfg = MAMLConfig(
            backbone=BackboneConfig(
                num_stages=2, num_filters=8, image_height=14, image_width=14,
                num_classes=5, per_step_bn_statistics=True, num_steps=2,
                **norm,
            ),
            number_of_training_steps_per_iter=2,
            number_of_evaluation_steps_per_iter=2,
        )
    else:
        # Flagship bundled run's shapes (bench.py): Omniglot 5-way, 64
        # filters, 5 inner steps, per-step BN.
        cfg = MAMLConfig(
            backbone=BackboneConfig(
                num_stages=4, num_filters=64, image_height=28, image_width=28,
                num_classes=5, per_step_bn_statistics=True, num_steps=5,
                **norm,
            ),
            number_of_training_steps_per_iter=5,
            number_of_evaluation_steps_per_iter=5,
        )
    learner_cls = {
        "maml": MAMLFewShotLearner,
        "anil": ANILLearner,
        "protonets": ProtoNetsLearner,
    }[family]
    learner = learner_cls(cfg)
    state = learner.init_inference_state(jax.random.PRNGKey(0))
    return ServingAPI(
        learner,
        state,
        ServeConfig(
            meta_batch_size=max_batch,
            max_wait_ms=max_wait_ms,
            cache_capacity=cache,
            tier_dir=tier_dir,
            geometry_lattice=(
                tuple(geometry_lattice) if geometry_lattice else None
            ),
        ),
    )


def episode_pool(api, n: int, shot: int = 1, query: int = 15, seed: int = 0):
    """``n`` distinct synthetic episodes at the served way/shot/query —
    geometry derived from the api; generation shared with the loadtest
    harness (one synthesis implementation, not two drifting copies)."""
    from tools.serve_loadtest import synth_episodes

    bb = api.engine.learner.cfg.backbone
    return synth_episodes(
        n,
        way=bb.num_classes,
        shot=shot,
        query=query,
        image_shape=(bb.image_channels, bb.image_height, bb.image_width),
        seed=seed,
    )


def offered_qps(
    api, episodes, budget_s: float, threads: int, errors: dict | None = None
) -> float:
    """SUCCESSFUL episodes/s with ``threads`` concurrent clients cycling
    ``episodes``. Failed requests (sheds, deadlines, dispatch errors) are
    tallied into ``errors`` (type name -> count) instead of silently
    inflating the rate — an overloaded bench must not report
    healthy-looking qps. Failures back off briefly: a synchronous shed
    costs no device time, and 8 clients spinning at exception-throw speed
    would burn the host and distort the very measurement the counters
    exist for."""
    from howtotrainyourmamlpytorch_tpu.serve.errors import ServeError

    stop_at = time.perf_counter() + budget_s
    counts = [0] * threads
    failures: list[dict] = [{} for _ in range(threads)]

    def client(tid: int) -> None:
        i = tid
        while time.perf_counter() < stop_at:
            xs, ys, xq = episodes[i % len(episodes)]
            try:
                api.classify(xs, ys, xq)
                counts[tid] += 1
            except (ServeError, TimeoutError) as exc:
                name = type(exc).__name__
                failures[tid][name] = failures[tid].get(name, 0) + 1
                time.sleep(0.002)
            i += threads

    t0 = time.perf_counter()
    with ThreadPoolExecutor(threads) as pool:
        list(pool.map(client, range(threads)))
    if errors is not None:
        for per_thread in failures:
            for name, count in per_thread.items():
                errors[name] = errors.get(name, 0) + count
    return sum(counts) / (time.perf_counter() - t0)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--tiny", action="store_true",
                        help="CI-sized model instead of the flagship shapes")
    parser.add_argument("--budget-s", type=float, default=5.0)
    parser.add_argument("--max-batch", type=int, default=4)
    parser.add_argument("--threads", type=int, default=8)
    parser.add_argument("--shot", type=int, default=1)
    parser.add_argument("--query", type=int, default=15)
    parser.add_argument("--slo-p99-ms", type=float, default=5000.0,
                        help="loadtest p99 budget (CPU-container default)")
    parser.add_argument("--error-slo", type=float, default=0.02)
    parser.add_argument("--skip-loadtest", action="store_true",
                        help="skip the resilience loadtest phase")
    parser.add_argument("--skip-tier", action="store_true",
                        help="skip the durable-tier warm-respawn phase")
    parser.add_argument("--skip-zoo", action="store_true",
                        help="skip the learner-zoo phase (the "
                        "protonets_serve_qps / anil_adapt_p50_ms keys)")
    parser.add_argument("--geometry-mix",
                        default="2x1x3,3x1x5,3x2x8,4x2x10,5x1x15,5x2x15",
                        help="comma-separated WxSxQ triples streamed "
                        "through a geometry-lattice engine (seeded "
                        "data.geometry_mix_episodes traffic); 'off' "
                        "disables the phase")
    parser.add_argument("--geometry-lattice", default="5x1x15,5x2x15",
                        help="declared WxSxQ bucket lattice for the "
                        "geometry phase — the fixed program set the mix "
                        "must coarsen onto")
    opts = parser.parse_args(argv)

    import jax

    api = build_api(opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512)
    way = api.engine.learner.cfg.backbone.num_classes
    api.engine.warmup([(way, opts.shot, opts.query)])

    # Cold path: every episode must pay the inner loop. The pool cycles, so
    # the cache is disabled for this phase (capacity 0 = no store) — a long
    # budget would otherwise wrap the pool and silently measure hits.
    bench_errors: dict[str, int] = {}
    cold_pool = episode_pool(api, n=64, shot=opts.shot, query=opts.query)
    api.engine.cache.clear()
    api.engine.cache.capacity = 0
    serve_qps = offered_qps(
        api, cold_pool, opts.budget_s, opts.threads, errors=bench_errors
    )
    api.engine.cache.capacity = 512
    adapt = api.metrics.adapt_latency.snapshot()
    classify = api.metrics.classify_latency.snapshot()

    # Hot path: one episode repeated — every request hits the cache.
    # Measured as PAIRED alternating windows, half with a structured event
    # sink installed (the engine then buffers one serve_dispatch event per
    # device dispatch — telemetry/events.py): each pair runs back-to-back
    # so its overhead delta sees the same machine state, pair order
    # alternates so host drift cancels, and telemetry_overhead_pct is the
    # median of per-pair deltas (an unpaired sequential comparison just
    # measures shared-host noise — same protocol as
    # tools/telemetry_report.py --overhead-bench).
    import statistics
    import tempfile

    from howtotrainyourmamlpytorch_tpu.telemetry import EventLog
    from howtotrainyourmamlpytorch_tpu.telemetry import (
        events as telemetry_events,
    )

    hot_pool = episode_pool(api, n=1, shot=opts.shot, query=opts.query, seed=7)
    xs, ys, xq = hot_pool[0]
    api.classify(xs, ys, xq)  # prime the cache entry
    log = EventLog(
        os.path.join(
            tempfile.mkdtemp(prefix="serve_telemetry_"), "telemetry.jsonl"
        )
    )
    hot_windows = 3
    per_window = opts.budget_s / (2 * hot_windows)
    plain_rates, telemetry_rates, pair_overheads = [], [], []
    for w in range(hot_windows):
        pair = {}
        order = (False, True) if w % 2 == 0 else (True, False)
        for with_sink in order:
            previous_sink = telemetry_events.install(log if with_sink else None)
            try:
                rate = offered_qps(
                    api, hot_pool, per_window, opts.threads,
                    errors=bench_errors,
                )
            finally:
                telemetry_events.install(previous_sink)
            pair[with_sink] = rate
            (telemetry_rates if with_sink else plain_rates).append(rate)
        pair_overheads.append(
            (pair[False] - pair[True]) / pair[False] * 100.0
        )
    log.flush()
    cache_hit_qps = statistics.median(plain_rates)
    telemetry_qps = statistics.median(telemetry_rates)
    telemetry_overhead_pct = statistics.median(pair_overheads)

    # Lock-sanitizer overhead (utils/locksan.py): same paired-window
    # protocol as the telemetry key. The sanitizer instruments locks at
    # CREATION time, so a second identical API is built inside an active
    # sanitizer — its engine/batcher/cache/metrics locks are all
    # instrumented (deactivation restores the factories but instrumented
    # locks keep recording) — and the hot path alternates between the
    # plain and the sanitized instance. The observed acquisition-order
    # graph rides along: serve_locksan_cycles must be 0 on every run.
    from howtotrainyourmamlpytorch_tpu.utils.locksan import LockSanitizer

    # BOTH instances are built fresh so their internal state (latency
    # ring buffers, cache fill, batcher margin history) ages identically
    # — comparing a fresh sanitized API against the run's aged primary
    # would measure instance age, not the sanitizer (observed: a fresh
    # instance is ~40% FASTER than one whose 2048-sample windows are
    # full, dwarfing any real overhead).
    san = LockSanitizer()
    api_plain2 = build_api(
        opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512
    )
    with san:
        api_san = build_api(
            opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512
        )
    for pair_api in (api_plain2, api_san):
        sanitized_api = pair_api is api_san
        if sanitized_api:
            san.activate()
        try:
            pair_api.engine.warmup([(way, opts.shot, opts.query)])
            pair_api.classify(xs, ys, xq)  # prime the cache entry
            # Full-window settle: a fresh instance speeds up considerably
            # over its first seconds (latency windows filling, allocator
            # steady state); measuring before the curve flattens poisons
            # the first pair.
            offered_qps(pair_api, hot_pool, per_window, opts.threads)
        finally:
            if sanitized_api:
                san.deactivate()
    locksan_windows = hot_windows + 2  # outvote any residual warm-in pair
    san_plain_rates, san_rates, san_pair_overheads = [], [], []
    for w in range(locksan_windows):
        pair = {}
        order = (False, True) if w % 2 == 0 else (True, False)
        for sanitized in order:
            # Sanitized windows run with the factories ACTIVE, exactly
            # like the tier-1 autouse fixture: the per-request cost (each
            # batcher submit creates a Future whose lock comes from the
            # threading factory, plus a creation-site frame walk) must be
            # inside the measurement, not just the construction-time
            # locks of build_api.
            if sanitized:
                san.activate()
            try:
                rate = offered_qps(
                    api_san if sanitized else api_plain2, hot_pool,
                    per_window, opts.threads, errors=bench_errors,
                )
            finally:
                if sanitized:
                    san.deactivate()
            pair[sanitized] = rate
            (san_rates if sanitized else san_plain_rates).append(rate)
        san_pair_overheads.append(
            (pair[False] - pair[True]) / pair[False] * 100.0
        )
    serve_locksan_qps = statistics.median(san_rates)
    serve_locksan_plain_qps = statistics.median(san_plain_rates)
    serve_locksan_overhead_pct = statistics.median(san_pair_overheads)
    serve_locksan_cycles = len(san.cycles())
    api_plain2.close()
    api_san.close()

    # Durable-tier phase: cold vs warm replica bring-up, and the disk-tier
    # hit rate. A first engine on a fresh tier dir pays real XLA compiles
    # and real adapts (serve_cold_ready_s) and primes the tier; a second
    # engine on the SAME dir deserializes its executables and rehydrates
    # its artifacts (serve_replica_ready_s) — the respawn-time receipt the
    # bench gate holds against the cold build. serve_tier_hit_qps then
    # serves with RAM capacity 0, so EVERY hit is a verified read from the
    # spill (CRC + fingerprint per request), the worst-case disk tier.
    tier_stats = None
    serve_cold_ready_s = serve_replica_ready_s = serve_tier_hit_qps = None
    if not opts.skip_tier:
        tier_root = tempfile.mkdtemp(prefix="serve_tier_bench_")
        t0 = time.perf_counter()
        api_cold = build_api(
            opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512,
            tier_dir=tier_root,
        )
        api_cold.engine.warmup([(way, opts.shot, opts.query)])
        serve_cold_ready_s = time.perf_counter() - t0
        tier_pool_eps = episode_pool(
            api_cold, n=16, shot=opts.shot, query=opts.query, seed=11
        )
        for xs_, ys_, xq_ in tier_pool_eps:  # prime the spill
            api_cold.classify(xs_, ys_, xq_)
        api_cold.close()
        t0 = time.perf_counter()
        api_warm = build_api(
            opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512,
            tier_dir=tier_root,
        )
        api_warm.engine.warmup([(way, opts.shot, opts.query)])
        serve_replica_ready_s = time.perf_counter() - t0
        api_warm.engine.cache.clear()
        api_warm.engine.cache.capacity = 0  # force every probe to disk
        serve_tier_hit_qps = offered_qps(
            api_warm, tier_pool_eps, max(1.0, opts.budget_s / 4),
            opts.threads, errors=bench_errors,
        )
        tier_stats = api_warm.engine.tier_stats()
        api_warm.close()

    # Resilience phase: open-loop Poisson loadtest against a 2-replica
    # LocalReplica pool with a replica kill injected mid-stream — the
    # "survives overload and replica death" keys are measured, not claimed.
    loadtest_result = None
    if not opts.skip_loadtest:
        from howtotrainyourmamlpytorch_tpu.serve.pool import (
            PoolConfig,
            ReplicaPool,
        )
        from howtotrainyourmamlpytorch_tpu.serve.resilience.replica import (
            LocalReplica,
        )
        from howtotrainyourmamlpytorch_tpu.utils import faultinject
        from tools.serve_loadtest import run_loadtest, synth_episodes

        way_ = api.engine.learner.cfg.backbone.num_classes

        def replica_factory(index: int) -> LocalReplica:
            replica_api = build_api(
                opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512
            )
            replica_api.engine.warmup([(way_, opts.shot, opts.query)])
            return LocalReplica(replica_api, replica_id=f"bench-{index}")

        lt_pool = ReplicaPool(
            replica_factory,
            PoolConfig(
                n_replicas=2, health_interval_s=0.1,
                restart_backoff_s=0.1, min_uptime_s=0.5,
            ),
        )
        if not lt_pool.wait_ready(timeout=300.0):
            lt_pool.close()
            raise RuntimeError(
                "loadtest replica pool never became healthy — a pool-boot "
                "failure, not a serving-SLO result"
            )
        bb = api.engine.learner.cfg.backbone
        lt_rate = max(2.0, round(serve_qps, 1))
        lt_duration = max(2.0, opts.budget_s / 2)
        faultinject.activate(
            faultinject.FaultPlan(
                replica_kill_at_request=max(
                    3, int(lt_rate * lt_duration / 3)
                )
            )
        )
        try:
            loadtest_result = run_loadtest(
                lt_pool,
                synth_episodes(
                    32, way=way_, shot=opts.shot, query=opts.query,
                    image_shape=(
                        bb.image_channels, bb.image_height, bb.image_width,
                    ),
                ),
                rate_qps=lt_rate,
                duration_s=lt_duration,
                p99_budget_ms=opts.slo_p99_ms,
                error_slo=opts.error_slo,
            )
        finally:
            faultinject.deactivate()
            lt_pool.close()

    # Learner-zoo phase: the other two families through the SAME serving
    # pipeline and synthesis. ``protonets_serve_qps`` is the metric tier's
    # headline — "adapt" is one embedding pass plus a class mean, so the
    # cold path should sit far above MAML's inner-loop qps;
    # ``anil_adapt_p50_ms`` is the head-only inner loop's dispatch
    # latency, the ANIL-vs-MAML serving lever in one number.
    protonets_serve_qps = anil_adapt_p50_ms = None
    if not opts.skip_zoo:
        zoo_budget = max(1.0, opts.budget_s / 4)
        api_pn = build_api(
            opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512,
            family="protonets",
        )
        api_pn.engine.warmup([(way, opts.shot, opts.query)])
        pn_pool = episode_pool(
            api_pn, n=64, shot=opts.shot, query=opts.query, seed=23
        )
        api_pn.engine.cache.clear()
        api_pn.engine.cache.capacity = 0  # cold: every episode pays adapt
        protonets_serve_qps = offered_qps(
            api_pn, pn_pool, zoo_budget, opts.threads, errors=bench_errors
        )
        api_pn.close()
        api_anil = build_api(
            opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512,
            family="anil",
        )
        api_anil.engine.warmup([(way, opts.shot, opts.query)])
        anil_pool = episode_pool(
            api_anil, n=64, shot=opts.shot, query=opts.query, seed=29
        )
        api_anil.engine.cache.clear()
        api_anil.engine.cache.capacity = 0
        offered_qps(
            api_anil, anil_pool, zoo_budget, opts.threads,
            errors=bench_errors,
        )
        anil_adapt_p50_ms = api_anil.metrics.adapt_latency.snapshot()[
            "p50_ms"
        ]
        api_anil.close()

    # Geometry phase: a mixed (way, shot, query) stream through a
    # declared-lattice engine. The receipt is ``geometry_mix_compiles``:
    # total program traces after serving EVERY geometry in the mix, which
    # must stay at the warmup bound (adapt + classify per lattice bucket)
    # — heterogeneous traffic must not mint programs.
    geometry_keys = None
    if opts.geometry_mix and opts.geometry_mix != "off":
        from howtotrainyourmamlpytorch_tpu.data import geometry_mix_episodes

        geo_lattice = parse_geometries(opts.geometry_lattice)
        geo_mix = parse_geometries(opts.geometry_mix)
        api_geo = build_api(
            opts.tiny, opts.max_batch, max_wait_ms=2.0, cache=512,
            geometry_lattice=geo_lattice,
        )
        api_geo.engine.warmup()  # every lattice bucket
        bb_geo = api_geo.engine.learner.cfg.backbone
        geo_eps = geometry_mix_episodes(
            4 * len(geo_mix), geo_mix,
            image_shape=(
                bb_geo.image_channels, bb_geo.image_height,
                bb_geo.image_width,
            ),
            seed=31,
        )
        t0 = time.perf_counter()
        for xs_, ys_, xq_ in geo_eps:
            api_geo.classify(xs_, ys_, xq_)
        geo_wall = time.perf_counter() - t0
        geo_table = api_geo.engine.compile_table()
        geo_snap = api_geo.metrics.snapshot()
        geometry_keys = {
            "geometry_mix_compiles": sum(geo_table.values()),
            "geometry_mix_buckets": len(api_geo.engine.geometry.lattice),
            "geometry_mix_geometries": len(set(geo_mix)),
            "geometry_mix_qps": round(len(geo_eps) / geo_wall, 3),
            "geometry_coarsened_total": geo_snap["geometry_coarsened_total"],
        }
        api_geo.close()

    compile_table = api.engine.compile_table()
    requests_offered = api.metrics.requests_total.value
    result = {
        "metric": "serve_qps",
        "value": round(serve_qps, 3),
        "unit": "episodes/s",
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "tiny": bool(opts.tiny),
        "meta_batch_size": opts.max_batch,
        "threads": opts.threads,
        "bucket": f"{way}x{opts.shot}x{opts.query}",
        "serve_qps": round(serve_qps, 3),
        "serve_cache_hit_qps": round(cache_hit_qps, 3),
        "serve_adapt_p50_ms": round(adapt["p50_ms"], 3),
        "serve_adapt_p99_ms": round(adapt["p99_ms"], 3),
        "serve_classify_p50_ms": round(classify["p50_ms"], 3),
        "serve_cache_hit_rate_final": round(
            api.metrics.cache_hit_rate(), 4
        ),
        "serve_telemetry_qps": round(telemetry_qps, 3),
        "telemetry_overhead_pct": round(telemetry_overhead_pct, 3),
        "telemetry_pair_overheads_pct": [
            round(o, 3) for o in pair_overheads
        ],
        "serve_locksan_qps": round(serve_locksan_qps, 3),
        "serve_locksan_plain_qps": round(serve_locksan_plain_qps, 3),
        "serve_locksan_overhead_pct": round(serve_locksan_overhead_pct, 3),
        "locksan_pair_overheads_pct": [
            round(o, 3) for o in san_pair_overheads
        ],
        "serve_locksan_cycles": serve_locksan_cycles,
        "serve_compiles": {
            "programs": len(compile_table),
            "total_traces": sum(compile_table.values()),
        },
        # Honesty keys: the offered phases can no longer hide failures.
        "serve_error_rate": round(
            api.metrics.request_errors.value / requests_offered, 6
        ) if requests_offered else 0.0,
        "serve_errors_by_type": dict(sorted(bench_errors.items())),
        "serve_shed_total": api.metrics.shed_total.value,
        "serve_deadline_exceeded_total": (
            api.metrics.deadline_exceeded_total.value
        ),
    }
    if protonets_serve_qps is not None:
        result.update(
            {
                "protonets_serve_qps": round(protonets_serve_qps, 3),
                "anil_adapt_p50_ms": round(anil_adapt_p50_ms, 3),
            }
        )
    if geometry_keys is not None:
        result.update(geometry_keys)
    if serve_cold_ready_s is not None:
        result.update(
            {
                "serve_cold_ready_s": round(serve_cold_ready_s, 3),
                "serve_replica_ready_s": round(serve_replica_ready_s, 3),
                "serve_tier_hit_qps": round(serve_tier_hit_qps, 3),
                "serve_tier_stats": tier_stats,
            }
        )
    if loadtest_result is not None:
        result.update(
            {
                "serve_slo_p99_ms": loadtest_result["serve_slo_p99_ms"],
                "serve_loadtest_p99_ms": (
                    loadtest_result["serve_loadtest_p99_ms"]
                ),
                "serve_loadtest_qps": loadtest_result["serve_loadtest_qps"],
                "serve_loadtest_error_rate": (
                    loadtest_result["serve_error_rate"]
                ),
                "serve_recovery_s": loadtest_result["serve_recovery_s"],
                "serve_slo_pass": loadtest_result["slo_pass"],
            }
        )
    print(json.dumps(result))
    api.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
