"""MAML/MAML++ training entry point.

Mirrors the reference's 15-line composition (``train_maml_system.py:1-15``):
args -> model -> dataset bootstrap -> ExperimentBuilder -> run_experiment().
Usage: ``python train_maml_system.py --name_of_args_json_file <cfg.json>``
(the reference's experiment config JSONs run unchanged).
"""

from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
from howtotrainyourmamlpytorch_tpu.experiment_builder import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.models import MAMLFewShotLearner
from howtotrainyourmamlpytorch_tpu.parallel import (
    default_mesh_from_args,
    initialize_distributed_from_argv,
)
from howtotrainyourmamlpytorch_tpu.utils.dataset_tools import maybe_unzip_dataset
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
    args_to_maml_config,
    get_args,
)

if __name__ == "__main__":
    # Multi-host: must run before any backend use so the mesh spans all
    # hosts' chips (no-op without --coordinator_address/--num_processes
    # flags, their config-JSON keys, or the JAX_* env equivalents).
    initialize_distributed_from_argv()
    args, device = get_args()
    model = MAMLFewShotLearner(
        cfg=args_to_maml_config(args), mesh=default_mesh_from_args(args)
    )
    maybe_unzip_dataset(args)
    maml_system = ExperimentBuilder(
        model=model, data=MetaLearningSystemDataLoader, args=args, device=device
    )
    maml_system.run_experiment()
