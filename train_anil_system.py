"""ANIL entry point: MAML's outer loop, inner loop restricted to the
classifier head (Raghu et al., "Rapid Learning or Feature Reuse?")."""

from howtotrainyourmamlpytorch_tpu.data import MetaLearningSystemDataLoader
from howtotrainyourmamlpytorch_tpu.experiment_builder import ExperimentBuilder
from howtotrainyourmamlpytorch_tpu.parallel import (
    default_mesh_from_args,
    initialize_distributed_from_argv,
)
from howtotrainyourmamlpytorch_tpu.models import ANILLearner
from howtotrainyourmamlpytorch_tpu.utils.dataset_tools import maybe_unzip_dataset
from howtotrainyourmamlpytorch_tpu.utils.parser_utils import (
    args_to_maml_config,
    get_args,
)

if __name__ == "__main__":
    # Multi-host bring-up BEFORE any device probe (no-op without an
    # explicit flag/config/env signal — parallel/distributed.py).
    initialize_distributed_from_argv()
    args, device = get_args()
    model = ANILLearner(
        cfg=args_to_maml_config(args),
        mesh=default_mesh_from_args(args),
    )
    maybe_unzip_dataset(args)
    system = ExperimentBuilder(
        model=model, data=MetaLearningSystemDataLoader, args=args, device=device
    )
    system.run_experiment()
