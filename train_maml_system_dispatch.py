"""Internal helper for sequential golden runs (not part of the public CLI).

Runs a config's entry point twice — the train phase pauses via sys.exit
after total_epochs_before_pause (reference semantics), the second invocation
resumes and runs the final top-5-ensemble test eval. Exit code is the worst
of the two phases."""
import subprocess
import sys

cfg = sys.argv[1]
extra = sys.argv[2:]  # forwarded to the entry point (e.g. --matmul_precision)
entry = ("train_gradient_descent_system.py" if "gradient-descent" in cfg
         else "train_matching_nets_system.py" if "matching-nets" in cfg
         else "train_maml_system.py")
codes = []
for phase in ("train", "test"):
    print(f"--- {cfg}: {phase} phase via {entry}", flush=True)
    proc = subprocess.run(
        [sys.executable, "-u", entry, "--name_of_args_json_file",
         f"experiment_config/{cfg}.json", *extra], check=False,
    )
    codes.append(proc.returncode)
sys.exit(max(codes))
