"""Internal helper for sequential golden runs (not part of the public CLI).

Runs a config's entry point in phases until the final top-5-ensemble test
eval has been produced. Two uses:

* classic pause/resume: the train phase exits via sys.exit after
  ``total_epochs_before_pause`` epochs (reference semantics,
  ``experiment_builder.py:365-368`` there); the next invocation resumes from
  the ``latest`` checkpoint and, once all epochs are done, runs the test
  ensemble.
* bounded-RSS segmented execution (``--pause_every N``): the axon device
  tunnel leaks every host->device transfer's staging buffer host-side
  (~0.7-1.5 GB/epoch at 20-way shapes — PERF_NOTES.md), which OOMs week-long
  runs. Restarting the process every N epochs caps RSS at ~N epochs' leak;
  checkpoint+resume is exact (seed fast-forward, tested), so segmented
  training is bit-identical to a single process.

Progress is tracked via the experiment's ``logs/summary_statistics.csv`` row
count; a phase that makes no progress twice in a row aborts (rc of that
phase, or 1 if it reported success while stuck).
"""
import json
import os
import subprocess
import sys


def main() -> int:
    argv = sys.argv[1:]
    cfg = argv[0]
    extra = argv[1:]
    pause_every = None
    if "--pause_every" in extra:
        i = extra.index("--pause_every")
        pause_every = int(extra[i + 1])
        extra = extra[:i] + extra[i + 2 :]
        if pause_every < 1:
            raise SystemExit(f"--pause_every must be >= 1, got {pause_every}")

    entry = ("train_gradient_descent_system.py" if "gradient-descent" in cfg
             else "train_matching_nets_system.py" if "matching-nets" in cfg
             else "train_maml_system.py")
    # Canonical configs live in experiment_config/ (the reference's 38-file
    # surface, content-tested); local variants (bf16, resnet12, ...) in
    # experiment_config_local/ so regeneration identity stays intact.
    for d in ("experiment_config", "experiment_config_local"):
        cfg_path = f"{d}/{cfg}.json"
        if os.path.exists(cfg_path):
            break
    else:
        raise FileNotFoundError(f"no config named {cfg} in experiment_config"
                                "{,_local}/")
    with open(cfg_path) as f:
        cfg_dict = json.load(f)
    exp_name = cfg_dict["experiment_name"]
    total_epochs = int(cfg_dict.get("total_epochs", 100))
    summary_csv = os.path.join(exp_name, "logs", "summary_statistics.csv")
    test_csv = os.path.join(exp_name, "logs", "test_summary.csv")

    def epochs_logged() -> int:
        try:
            with open(summary_csv) as f:
                return max(sum(1 for _ in f) - 1, 0)
        except OSError:
            return 0

    if os.path.exists(test_csv):
        # Idempotent resume of a finished run: nothing to do. Explicit, so
        # a stale test_summary.csv can't silently mask an intended re-run —
        # delete the experiment dir (or its test_summary.csv) to redo.
        print(f"--- {cfg}: test eval already present at {test_csv}; "
              "nothing to run", flush=True)
        return 0

    patched_path = None
    if pause_every is not None:
        # A --total_epochs_before_pause CLI flag would be OVERRIDDEN by the
        # config JSON (JSON wins over every flag except continue_from/
        # gpu_to_use — reference semantics, utils/parser_utils.py). Write a
        # patched config instead; experiment_name is unchanged so logs,
        # checkpoints and resume behave identically.
        import tempfile

        cfg_dict["total_epochs_before_pause"] = pause_every
        patched = tempfile.NamedTemporaryFile(
            "w", suffix=f"_{cfg}.json", delete=False
        )
        json.dump(cfg_dict, patched)
        patched.close()
        cfg_path = patched_path = patched.name

    try:
        max_phases = 2 * (total_epochs // (pause_every or total_epochs) + 2)
        # Requeue exits (rc 75, experiment_builder.REQUEUE_EXIT_CODE) are
        # preemption-safe: an emergency checkpoint was written mid-epoch, so
        # re-entering is always progress even though no epoch row landed.
        # They get their own (generous) budget instead of consuming the
        # phase budget — a heavily-preempted long run must not abort as
        # "budget exhausted" while advancing monotonically.
        max_requeues = 100
        stalled = phase = requeues = 0
        rc = 0
        while phase < max_phases and requeues < max_requeues:
            before = epochs_logged()
            print(f"--- {cfg}: phase {phase} via {entry} "
                  f"(epochs logged: {before}/{total_epochs})", flush=True)
            proc = subprocess.run(
                [sys.executable, "-u", entry, "--name_of_args_json_file",
                 cfg_path, *extra], check=False,
            )
            rc = proc.returncode
            if os.path.exists(test_csv):
                break
            if rc == 75:
                stalled = 0
                requeues += 1
                continue
            phase += 1
            if epochs_logged() <= before:
                stalled += 1
                if stalled >= 2:
                    print(f"--- {cfg}: no progress across two phases, "
                          "aborting", flush=True)
                    return rc or 1
            else:
                stalled = 0
        if not os.path.exists(test_csv):
            print(f"--- {cfg}: phase budget exhausted without test eval",
                  flush=True)
            return rc or 1
        print(f"--- {cfg}: done ({epochs_logged()} epochs + test eval, "
              f"final phase rc {rc})", flush=True)
        # Exit-code fidelity: the phase that produced the test eval still
        # decides the exit code (a teardown failure must not be masked).
        return rc
    finally:
        if patched_path is not None:
            try:
                os.unlink(patched_path)
            except OSError:
                pass


if __name__ == "__main__":
    sys.exit(main())
