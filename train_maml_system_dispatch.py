"""Internal helper for sequential golden runs (not part of the public CLI).

Runs a config's entry point in phases until the final top-5-ensemble test
eval has been produced, supervising the training process like
``serve/pool.py`` supervises replicas. Uses:

* classic pause/resume: the train phase exits via sys.exit after
  ``total_epochs_before_pause`` epochs (reference semantics,
  ``experiment_builder.py:365-368`` there); the next invocation resumes from
  the ``latest`` checkpoint and, once all epochs are done, runs the test
  ensemble.
* bounded-RSS segmented execution (``--pause_every N``): the axon device
  tunnel leaks every host->device transfer's staging buffer host-side
  (~0.7-1.5 GB/epoch at 20-way shapes — PERF_NOTES.md), which OOMs week-long
  runs. Restarting the process every N epochs caps RSS at ~N epochs' leak;
  checkpoint+resume is exact (seed fast-forward, tested), so segmented
  training is bit-identical to a single process.
* preemption requeue (exit 75): an emergency checkpoint was written, the
  phase re-enters on the SAME mesh — its own generous budget
  (``--max_requeues``), separate from the phase budget.
* hang requeue-degraded (exit 76, ``utils/watchdog.py``): the watchdog
  detected a wedged dispatch and left a thread-stack diagnostic. The
  topology itself is suspect, so the next phase resumes the SAME
  experiment on the next-smaller viable mesh (8 -> 4 -> 2 -> 1 dp,
  honoring the global meta-batch and ``--task_chunk`` divisibility —
  ``parallel/mesh.degraded_dp_extent``), riding the mesh-portable
  checkpoint restore. Hangs draw on their OWN budget (``--max_hangs``):
  a hang-looping run must not eat the preemption budget, and vice versa.
  Repeated signal-deaths (two in a row — a crashing device looks like a
  dying worker, not a preemption) degrade the same way. After a phase
  completes cleanly on a degraded mesh, a RE-PROMOTION PROBE restores the
  next-larger extent for the following phase — transient topology faults
  heal; a re-hang simply degrades again, on budget. Every degrade/promote
  appends an audit row to ``<experiment>/logs/interruptions.csv``.

* multi-host fleet supervision (``--num_processes N``): each phase spawns N
  worker processes over a loopback coordinator (fresh free port per phase;
  rank 0 hosts the coordination service, every rank gets
  ``--coordinator_address/--num_processes/--process_id`` flags — bring-up
  flags beat config keys in ``parallel/distributed.py``, so the same
  config JSON drives any fleet size). HOST LOSS — any rank dead by signal,
  hung (rc 76 from the PR 10 watchdog, which fires on the surviving ranks
  when a peer's collective goes silent), or crashed — triggers COORDINATED
  SHUTDOWN of the survivors (grace for their own watchdog exit, then
  SIGTERM, then SIGKILL), a host-attributed audit row, and degraded-mesh
  auto-resume on the next-smaller viable process count
  (``parallel/mesh.degraded_process_count``) from the last published
  checkpoint — rank 0 is the single checkpoint writer, and checkpoints are
  mesh-portable, so a 2-host run resumes on 1 host bit-compatibly. Host
  losses draw on the ``--max_hangs`` budget (the topology is suspect); a
  fleet-wide preemption (every rank exits 75) draws on ``--max_requeues``
  and resumes the SAME fleet. After a clean degraded phase the
  re-promotion probe restores the previous fleet size.

``MAML_FAULTS`` (utils/faultinject.py) is consumed by the FIRST phase only:
env fault plans are one-shot per dispatcher run, so a requeued/degraded
phase replays clean instead of deterministically re-hitting the same
injected fault every restart. In fleet mode ``--fault_rank R`` targets the
plan at one rank (the kill-a-host chaos class needs exactly one host to
die); without it every rank inherits the plan.

Progress is tracked via the experiment's ``logs/summary_statistics.csv`` row
count; a phase that makes no progress twice in a row aborts (rc of that
phase, or 1 if it reported success while stuck).
"""
import json
import os
import signal
import subprocess
import sys
import time
import uuid

#: Preemption requeue (experiment_builder.REQUEUE_EXIT_CODE): emergency
#: checkpoint written, resume on the SAME mesh.
REQUEUE_EXIT_CODE = 75
#: Watchdog hang (utils/watchdog.HANG_EXIT_CODE): requeue but SUSPECT THE
#: TOPOLOGY — resume on the next-smaller viable mesh.
HANG_EXIT_CODE = 76
#: Run-scoped trace id env (telemetry/events.TRACE_ID_ENV — pinned equal
#: by tests/test_telemetry.py): exported once per dispatcher run so every
#: phase, and every rank of a fleet phase, stamps the SAME trace_id on its
#: telemetry — the whole elastic lifecycle (hangs, degrades, resumes)
#: merges into one timeline in ``tools/telemetry_report.py --fleet``.
TRACE_ID_ENV = "MAML_TRACE_ID"

#: Test hook: overrides which entry script a phase runs (the budget/degrade
#: policy is provable without compiling real XLA programs). Internal.
ENTRY_ENV = "MAML_DISPATCH_ENTRY"


def _pop_flag(extra, name, default, cast):
    if name in extra:
        i = extra.index(name)
        value = cast(extra[i + 1])
        del extra[i:i + 2]
        return value
    return default


def _heartbeat_progress(exp_name: str) -> tuple:
    """Last-known progress from the trainer heartbeat
    (``logs/status.json``, written atomically at forced-read boundaries —
    telemetry/heartbeat.py). Returns ``(current_iter, epoch)`` as strings
    for the audit row; empty strings when no (valid) heartbeat exists —
    the pre-heartbeat behavior of inferring nothing from exit codes."""
    try:
        from howtotrainyourmamlpytorch_tpu.telemetry.heartbeat import (
            read_heartbeat,
        )

        doc = read_heartbeat(os.path.join(exp_name, "logs", "status.json"))
    except Exception:  # noqa: BLE001 — auditing must not break supervision
        doc = None
    if not doc:
        return "", ""
    current_iter = doc.get("current_iter")
    epoch = doc.get("epoch")
    return (
        "" if current_iter is None else str(current_iter),
        "" if epoch is None else str(epoch),
    )


def _audit_row(exp_name: str, kind: str, process_index="",
               process_count="", when: float | None = None,
               current_iter="", epoch="") -> None:
    """Appends a dispatcher audit row to the experiment's interruptions
    CSV (same header the builder's preemption rows use, so one file holds
    the full interruption history). ``process_index``/``process_count``
    attribute host-loss rows to the rank that died; supervisor-policy rows
    (degrade/promote) leave them empty. ``current_iter``/``epoch`` carry
    the heartbeat's last-known progress (``_heartbeat_progress``) — the
    row says WHERE the run was lost, not just that it was. Rows align to
    the file's existing header so pre-multi-host experiments keep their
    4-column layout."""
    logs = os.path.join(exp_name, "logs")
    header = ("timestamp,signal,current_iter,epoch,"
              "process_index,process_count")
    try:
        os.makedirs(logs, exist_ok=True)
        path = os.path.join(logs, "interruptions.csv")
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write(header + "\n")
        with open(path) as f:
            n_cols = len(f.readline().rstrip("\n").split(","))
        row = [str(time.time() if when is None else when), str(kind),
               str(current_iter), str(epoch),
               str(process_index), str(process_count)][:max(n_cols, 4)]
        with open(path, "a") as f:
            f.write(",".join(row) + "\n")
    except OSError:
        pass  # auditing must not break supervision


def _resolved_dp(cfg_dict: dict, extra: list) -> int:
    """The dp extent the next phase will actually run: an explicit config/
    CLI value, else (lazily, only when a degrade decision needs it) the
    local-device fill the mesh builder would compute."""
    dp = int(cfg_dict.get("data_parallel_devices", 0) or 0)
    if dp <= 0 and "--data_parallel_devices" in extra:
        dp = int(extra[extra.index("--data_parallel_devices") + 1])
    if dp > 0:
        return dp
    import jax  # deliberate lazy import: only the degrade path pays it

    mp = int(cfg_dict.get("model_parallel_devices", 1) or 1)
    return max(len(jax.devices()) // max(mp, 1), 1)


def _global_batch(cfg_dict: dict) -> int:
    return (
        int(cfg_dict.get("num_of_gpus", 1) or 1)
        * int(cfg_dict.get("batch_size", 32))
        * int(cfg_dict.get("samples_per_iter", 1) or 1)
    )


def _next_smaller_dp(cfg_dict: dict, current_dp: int) -> int | None:
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import degraded_dp_extent

    return degraded_dp_extent(
        current_dp,
        global_batch=_global_batch(cfg_dict),
        task_chunk=int(cfg_dict.get("task_chunk", 0) or 0),
    )


def _next_smaller_procs(
    cfg_dict: dict, current_procs: int, local_devices: int
) -> int | None:
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import (
        degraded_process_count,
    )

    return degraded_process_count(
        current_procs,
        global_batch=_global_batch(cfg_dict),
        local_devices=local_devices,
        task_chunk=int(cfg_dict.get("task_chunk", 0) or 0),
    )


def _free_port() -> int:
    from howtotrainyourmamlpytorch_tpu.parallel.distributed import (
        find_free_port,
    )

    return find_free_port()


def _run_fleet(
    entry: str,
    run_cfg_path: str,
    extra: list,
    num_processes: int,
    child_env: dict,
    fault_rank: int | None,
    grace_s: float,
) -> tuple[list[int], int | None, float | None]:
    """One multi-host phase: spawn ``num_processes`` ranks over a fresh
    loopback coordinator and supervise to fleet exit. Once ANY rank exits,
    the fleet is no longer whole — survivors get ``grace_s`` to exit on
    their own (a peer-loss hang ends in the rank's OWN watchdog rc 76,
    which is evidence worth keeping), then SIGTERM, then SIGKILL. Returns
    ``(per-rank exit codes, first-exit rank, first-exit unix time)`` —
    when the fleet dies, the FIRST rank to exit is the root cause (the
    lost host); later deaths are symptoms (peer-loss watchdog exits, or
    this supervisor's own shutdown), so exit ORDER is the attribution
    signal, not exit codes — and the first-exit TIME is the host-loss
    instant recovery is measured from.
    ``num_processes == 1`` spawns a plain single-process child (no
    distributed flags — opt-in stays explicit)."""
    dist_flags: list[str] = []
    if num_processes > 1:
        addr = f"127.0.0.1:{_free_port()}"
        dist_flags = [
            "--coordinator_address", addr,
            "--num_processes", str(num_processes),
        ]
    procs: list[subprocess.Popen] = []
    for rank in range(num_processes):
        env = dict(child_env)
        if fault_rank is not None and rank != fault_rank:
            env.pop("MAML_FAULTS", None)
        rank_flags = dist_flags + (
            ["--process_id", str(rank)] if num_processes > 1 else []
        )
        procs.append(subprocess.Popen(
            [sys.executable, "-u", entry, "--name_of_args_json_file",
             run_cfg_path, *extra, *rank_flags],
            env=env,
        ))
    first_exit_t: float | None = None
    first_exit_rank: int | None = None
    first_exit_wall: float | None = None
    terminated = killed = False
    while any(p.poll() is None for p in procs):
        if any(p.poll() is not None for p in procs):
            now = time.monotonic()
            if first_exit_t is None:
                first_exit_t = now
                first_exit_wall = time.time()
                first_exit_rank = next(
                    i for i, p in enumerate(procs) if p.poll() is not None
                )
            elif not terminated and now - first_exit_t > grace_s:
                for p in procs:
                    if p.poll() is None:
                        try:
                            p.send_signal(signal.SIGTERM)
                        except OSError:
                            pass
                terminated = True
                first_exit_t = now
            elif terminated and not killed and now - first_exit_t > 15.0:
                for p in procs:
                    if p.poll() is None:
                        try:
                            p.kill()
                        except OSError:
                            pass
                killed = True
        time.sleep(0.25)
    return [p.wait() for p in procs], first_exit_rank, first_exit_wall


def _classify_fleet(
    rcs: list[int], first_exit_rank: int | None
) -> tuple[int, int | None]:
    """Fleet exit codes -> (phase rc, failing rank). All-zero is success;
    a fleet-wide preemption (every rank 0/75, at least one 75) is a
    requeue; ANY rank dead-by-signal / hung (76) / crashed is a HOST LOSS
    (reported as the hang code — the topology is suspect). Attribution:
    the FIRST rank to exit abnormally is the root cause — later deaths
    are symptoms (peer-loss watchdog exits, supervisor shutdown)."""
    if all(rc == 0 for rc in rcs):
        return 0, None
    if all(rc in (0, REQUEUE_EXIT_CODE) for rc in rcs):
        return REQUEUE_EXIT_CODE, None
    # Anything else — dead-by-signal, hung (76), or a plain crash — is a
    # host loss: the fleet cannot make progress with that rank gone
    # either way, and the degraded resume (budget-bounded by
    # --max_hangs) is the recovery for all of them. A deterministic
    # code bug that crashes every fleet size exhausts the budget and
    # aborts rather than looping.
    bad = [
        rank for rank, rc in enumerate(rcs)
        if rc not in (0, REQUEUE_EXIT_CODE)
    ]
    blamed = first_exit_rank if first_exit_rank in bad else bad[0]
    return HANG_EXIT_CODE, blamed


def main() -> int:
    argv = sys.argv[1:]
    cfg = argv[0]
    extra = list(argv[1:])
    pause_every = _pop_flag(extra, "--pause_every", None, int)
    if pause_every is not None and pause_every < 1:
        raise SystemExit(f"--pause_every must be >= 1, got {pause_every}")
    # Requeue exits (rc 75) are preemption-safe: an emergency checkpoint
    # was written mid-epoch, so re-entering is always progress even though
    # no epoch row landed. They get their own (generous) budget instead of
    # consuming the phase budget — a heavily-preempted long run must not
    # abort as "budget exhausted" while advancing monotonically. Hang
    # exits (rc 76) get a SEPARATE budget for the same reason in reverse:
    # the two failure classes must not starve each other's recovery.
    max_requeues = _pop_flag(extra, "--max_requeues", 100, int)
    max_hangs = _pop_flag(extra, "--max_hangs", 8, int)
    # Multi-host fleet supervision: N worker processes per phase over a
    # loopback coordinator (0/1 = the classic single-process path).
    num_processes = _pop_flag(extra, "--num_processes", 0, int) or 0
    fault_rank = _pop_flag(extra, "--fault_rank", None, int)
    fleet_grace_s = _pop_flag(extra, "--fleet_grace_s", 30.0, float)
    fleet = num_processes > 1

    entry = os.environ.get(ENTRY_ENV) or (
        "train_gradient_descent_system.py" if "gradient-descent" in cfg
        else "train_matching_nets_system.py" if "matching-nets" in cfg
        else "train_maml_system.py")
    # Canonical configs live in experiment_config/ (the reference's 38-file
    # surface, content-tested); local variants (bf16, resnet12, ...) in
    # experiment_config_local/ so regeneration identity stays intact. A
    # direct .json path (chaos harness workdirs, ad-hoc fleets) is used
    # as-is.
    if cfg.endswith(".json") and os.path.exists(cfg):
        cfg_path = cfg
    else:
        for d in ("experiment_config", "experiment_config_local"):
            cfg_path = f"{d}/{cfg}.json"
            if os.path.exists(cfg_path):
                break
        else:
            raise FileNotFoundError(
                f"no config named {cfg} in experiment_config{{,_local}}/"
            )
    with open(cfg_path) as f:
        cfg_dict = json.load(f)
    exp_name = cfg_dict["experiment_name"]
    total_epochs = int(cfg_dict.get("total_epochs", 100))
    summary_csv = os.path.join(exp_name, "logs", "summary_statistics.csv")
    test_csv = os.path.join(exp_name, "logs", "test_summary.csv")

    def epochs_logged() -> int:
        try:
            with open(summary_csv) as f:
                return max(sum(1 for _ in f) - 1, 0)
        except OSError:
            return 0

    if os.path.exists(test_csv):
        # Idempotent resume of a finished run: nothing to do. Explicit, so
        # a stale test_summary.csv can't silently mask an intended re-run —
        # delete the experiment dir (or its test_summary.csv) to redo.
        print(f"--- {cfg}: test eval already present at {test_csv}; "
              "nothing to run", flush=True)
        return 0

    # Config-key overrides are written into a patched config file rather
    # than passed as flags: the JSON wins over every flag except
    # continue_from/gpu_to_use (reference semantics, utils/parser_utils.py),
    # so a flag could be silently overridden by the config. experiment_name
    # is unchanged so logs, checkpoints and resume behave identically.
    overrides: dict = {}
    if pause_every is not None:
        overrides["total_epochs_before_pause"] = pause_every
    patched_path = None
    run_cfg_path = cfg_path

    def write_patched():
        nonlocal patched_path, run_cfg_path
        import tempfile

        if patched_path is not None:
            try:
                os.unlink(patched_path)
            except OSError:
                pass
            patched_path = None
        if not overrides:
            run_cfg_path = cfg_path
            return
        cfg_tag = os.path.splitext(os.path.basename(cfg))[0]
        patched = tempfile.NamedTemporaryFile(
            "w", suffix=f"_{cfg_tag}.json", delete=False
        )
        json.dump({**cfg_dict, **overrides}, patched)
        patched.close()
        run_cfg_path = patched_path = patched.name

    write_patched()

    # Degraded-mesh state: dp extents (fleet mode: process counts) we
    # stepped down from, newest last — popped one level at each
    # re-promotion probe.
    promote_stack: list[int] = []
    # Fleet mode: the per-host device count is fixed by the hardware; a
    # degraded fleet keeps it and shrinks the dp extent proportionally.
    current_procs = num_processes if fleet else 1
    local_devices = (
        max(int(cfg_dict.get("data_parallel_devices", 0) or 0)
            // num_processes, 1)
        if fleet else 1
    )

    try:
        max_phases = 2 * (total_epochs // (pause_every or total_epochs) + 2)
        stalled = phase = requeues = hangs = signal_deaths = 0
        child_env = dict(os.environ)
        # One trace id for the whole supervised run (all phases, all
        # ranks): an inherited id wins — a higher-level orchestrator may
        # already have scoped the trace.
        child_env.setdefault(TRACE_ID_ENV, uuid.uuid4().hex[:16])
        rc = 0
        while (
            phase < max_phases
            and requeues < max_requeues
            and hangs < max_hangs
        ):
            before = epochs_logged()
            print(f"--- {cfg}: phase {phase} via {entry} "
                  f"(epochs logged: {before}/{total_epochs}"
                  + (f", fleet of {current_procs}" if fleet else "")
                  + ")", flush=True)
            bad_rank = None
            if fleet:
                rcs, first_exit_rank, first_exit_wall = _run_fleet(
                    entry, run_cfg_path, extra, current_procs,
                    child_env, fault_rank, fleet_grace_s,
                )
                rc, bad_rank = _classify_fleet(rcs, first_exit_rank)
                print(f"--- {cfg}: fleet rcs {rcs} -> phase rc {rc}",
                      flush=True)
            else:
                proc = subprocess.run(
                    [sys.executable, "-u", entry, "--name_of_args_json_file",
                     run_cfg_path, *extra], check=False, env=child_env,
                )
                rc = proc.returncode
            # Env fault plans are one-shot per dispatcher run: the phase
            # that just ran consumed them; a requeued/degraded phase must
            # replay clean, not re-hit the same injected fault forever.
            child_env.pop("MAML_FAULTS", None)
            if os.path.exists(test_csv):
                break
            if rc == REQUEUE_EXIT_CODE:
                stalled = signal_deaths = 0
                requeues += 1
                continue
            died_by_signal = rc < 0 or rc > 128
            signal_deaths = signal_deaths + 1 if died_by_signal else 0
            if rc == HANG_EXIT_CODE or signal_deaths >= 2:
                # Suspect the topology: a wedged dispatch (watchdog
                # diagnostic in logs/hang_stacks.txt), a device that
                # keeps killing its worker, or — fleet mode — a HOST LOSS
                # (any rank dead/hung; survivors were shut down in
                # coordination). Resume the same experiment on the
                # next-smaller viable mesh/fleet, from the last valid
                # checkpoint (mesh-portable restore).
                hangs += 1
                stalled = signal_deaths = 0
                # Last-known progress from the heartbeat: the audit row
                # records where the run was when the topology failed, not
                # just the exit code the failure produced.
                hb_iter, hb_epoch = _heartbeat_progress(exp_name)
                if fleet:
                    smaller = _next_smaller_procs(
                        cfg_dict, current_procs, local_devices
                    )
                    why = (f"host-loss:rank{bad_rank}"
                           if bad_rank is not None else "host-loss")
                    if smaller is not None:
                        promote_stack.append(current_procs)
                        # Stamped with the OBSERVED first-exit time: the
                        # row marks when the host was lost, not when this
                        # supervisor finished coordinating the shutdown —
                        # recovery time is measured from it.
                        _audit_row(
                            exp_name,
                            f"{why}-degrade:procs{current_procs}->"
                            f"procs{smaller}",
                            process_index=(
                                bad_rank if bad_rank is not None else ""
                            ),
                            process_count=current_procs,
                            when=first_exit_wall,
                            current_iter=hb_iter, epoch=hb_epoch,
                        )
                        print(f"--- {cfg}: {why} (rc {rc}); degrading "
                              f"fleet {current_procs} -> {smaller} "
                              "process(es), resuming from the last valid "
                              "checkpoint", flush=True)
                        current_procs = smaller
                        overrides["data_parallel_devices"] = (
                            smaller * local_devices
                        )
                        write_patched()
                    else:
                        _audit_row(
                            exp_name,
                            f"{why}-requeue:procs{current_procs}",
                            process_index=(
                                bad_rank if bad_rank is not None else ""
                            ),
                            process_count=current_procs,
                            when=first_exit_wall,
                            current_iter=hb_iter, epoch=hb_epoch,
                        )
                        print(f"--- {cfg}: {why} (rc {rc}) with no "
                              "smaller viable fleet; requeueing on the "
                              "same topology", flush=True)
                    continue
                current_dp = _resolved_dp(
                    {**cfg_dict, **overrides}, extra
                )
                smaller = _next_smaller_dp(cfg_dict, current_dp)
                why = ("hang" if rc == HANG_EXIT_CODE
                       else "repeated-signal-death")
                if smaller is not None:
                    promote_stack.append(current_dp)
                    overrides["data_parallel_devices"] = smaller
                    write_patched()
                    _audit_row(
                        exp_name,
                        f"{why}-degrade:dp{current_dp}->dp{smaller}",
                        current_iter=hb_iter, epoch=hb_epoch,
                    )
                    print(f"--- {cfg}: {why} (rc {rc}); degrading mesh "
                          f"dp{current_dp} -> dp{smaller} and resuming "
                          "from the last valid checkpoint", flush=True)
                else:
                    _audit_row(exp_name, f"{why}-requeue:dp{current_dp}",
                               current_iter=hb_iter, epoch=hb_epoch)
                    print(f"--- {cfg}: {why} (rc {rc}) with no smaller "
                          "viable mesh; requeueing on the same topology",
                          flush=True)
                continue
            phase += 1
            if epochs_logged() <= before:
                stalled += 1
                if stalled >= 2:
                    print(f"--- {cfg}: no progress across two phases, "
                          "aborting", flush=True)
                    return rc or 1
            else:
                stalled = 0
                if promote_stack:
                    # Re-promotion probe: the degraded mesh/fleet just
                    # completed a phase with real progress — try one step
                    # back up; a re-hang degrades again, on budget.
                    restored = promote_stack.pop()
                    if fleet:
                        current_procs = restored
                        overrides["data_parallel_devices"] = (
                            restored * local_devices
                        )
                        write_patched()
                        _audit_row(
                            exp_name, f"probe-promote:procs{restored}",
                            process_count=restored,
                        )
                        print(f"--- {cfg}: clean degraded phase; probing "
                              f"re-promotion to {restored} process(es)",
                              flush=True)
                    else:
                        overrides["data_parallel_devices"] = restored
                        write_patched()
                        _audit_row(exp_name, f"probe-promote:dp{restored}")
                        print(f"--- {cfg}: clean degraded phase; probing "
                              f"re-promotion to dp{restored}", flush=True)
        if hangs >= max_hangs:
            print(f"--- {cfg}: hang budget ({max_hangs}) exhausted, "
                  "aborting", flush=True)
            return rc or 1
        if not os.path.exists(test_csv):
            print(f"--- {cfg}: phase budget exhausted without test eval",
                  flush=True)
            return rc or 1
        print(f"--- {cfg}: done ({epochs_logged()} epochs + test eval, "
              f"final phase rc {rc})", flush=True)
        # Exit-code fidelity: the phase that produced the test eval still
        # decides the exit code (a teardown failure must not be masked).
        return rc
    finally:
        if patched_path is not None:
            try:
                os.unlink(patched_path)
            except OSError:
                pass


if __name__ == "__main__":
    sys.exit(main())
