"""Internal helper for sequential golden runs (not part of the public CLI).

Runs a config's entry point in phases until the final top-5-ensemble test
eval has been produced, supervising the training process like
``serve/pool.py`` supervises replicas. Uses:

* classic pause/resume: the train phase exits via sys.exit after
  ``total_epochs_before_pause`` epochs (reference semantics,
  ``experiment_builder.py:365-368`` there); the next invocation resumes from
  the ``latest`` checkpoint and, once all epochs are done, runs the test
  ensemble.
* bounded-RSS segmented execution (``--pause_every N``): the axon device
  tunnel leaks every host->device transfer's staging buffer host-side
  (~0.7-1.5 GB/epoch at 20-way shapes — PERF_NOTES.md), which OOMs week-long
  runs. Restarting the process every N epochs caps RSS at ~N epochs' leak;
  checkpoint+resume is exact (seed fast-forward, tested), so segmented
  training is bit-identical to a single process.
* preemption requeue (exit 75): an emergency checkpoint was written, the
  phase re-enters on the SAME mesh — its own generous budget
  (``--max_requeues``), separate from the phase budget.
* hang requeue-degraded (exit 76, ``utils/watchdog.py``): the watchdog
  detected a wedged dispatch and left a thread-stack diagnostic. The
  topology itself is suspect, so the next phase resumes the SAME
  experiment on the next-smaller viable mesh (8 -> 4 -> 2 -> 1 dp,
  honoring the global meta-batch and ``--task_chunk`` divisibility —
  ``parallel/mesh.degraded_dp_extent``), riding the mesh-portable
  checkpoint restore. Hangs draw on their OWN budget (``--max_hangs``):
  a hang-looping run must not eat the preemption budget, and vice versa.
  Repeated signal-deaths (two in a row — a crashing device looks like a
  dying worker, not a preemption) degrade the same way. After a phase
  completes cleanly on a degraded mesh, a RE-PROMOTION PROBE restores the
  next-larger extent for the following phase — transient topology faults
  heal; a re-hang simply degrades again, on budget. Every degrade/promote
  appends an audit row to ``<experiment>/logs/interruptions.csv``.

``MAML_FAULTS`` (utils/faultinject.py) is consumed by the FIRST phase only:
env fault plans are one-shot per dispatcher run, so a requeued/degraded
phase replays clean instead of deterministically re-hitting the same
injected fault every restart.

Progress is tracked via the experiment's ``logs/summary_statistics.csv`` row
count; a phase that makes no progress twice in a row aborts (rc of that
phase, or 1 if it reported success while stuck).
"""
import json
import os
import subprocess
import sys
import time

#: Preemption requeue (experiment_builder.REQUEUE_EXIT_CODE): emergency
#: checkpoint written, resume on the SAME mesh.
REQUEUE_EXIT_CODE = 75
#: Watchdog hang (utils/watchdog.HANG_EXIT_CODE): requeue but SUSPECT THE
#: TOPOLOGY — resume on the next-smaller viable mesh.
HANG_EXIT_CODE = 76

#: Test hook: overrides which entry script a phase runs (the budget/degrade
#: policy is provable without compiling real XLA programs). Internal.
ENTRY_ENV = "MAML_DISPATCH_ENTRY"


def _pop_flag(extra, name, default, cast):
    if name in extra:
        i = extra.index(name)
        value = cast(extra[i + 1])
        del extra[i:i + 2]
        return value
    return default


def _audit_row(exp_name: str, kind: str) -> None:
    """Appends a dispatcher audit row to the experiment's interruptions
    CSV (same 4-column header the builder's preemption rows use, so one
    file holds the full interruption history)."""
    logs = os.path.join(exp_name, "logs")
    try:
        os.makedirs(logs, exist_ok=True)
        path = os.path.join(logs, "interruptions.csv")
        if not os.path.exists(path):
            with open(path, "w") as f:
                f.write("timestamp,signal,current_iter,epoch\n")
        with open(path, "a") as f:
            f.write(f"{time.time()},{kind},,\n")
    except OSError:
        pass  # auditing must not break supervision


def _resolved_dp(cfg_dict: dict, extra: list) -> int:
    """The dp extent the next phase will actually run: an explicit config/
    CLI value, else (lazily, only when a degrade decision needs it) the
    local-device fill the mesh builder would compute."""
    dp = int(cfg_dict.get("data_parallel_devices", 0) or 0)
    if dp <= 0 and "--data_parallel_devices" in extra:
        dp = int(extra[extra.index("--data_parallel_devices") + 1])
    if dp > 0:
        return dp
    import jax  # deliberate lazy import: only the degrade path pays it

    mp = int(cfg_dict.get("model_parallel_devices", 1) or 1)
    return max(len(jax.devices()) // max(mp, 1), 1)


def _next_smaller_dp(cfg_dict: dict, current_dp: int) -> int | None:
    from howtotrainyourmamlpytorch_tpu.parallel.mesh import degraded_dp_extent

    global_batch = (
        int(cfg_dict.get("num_of_gpus", 1) or 1)
        * int(cfg_dict.get("batch_size", 32))
        * int(cfg_dict.get("samples_per_iter", 1) or 1)
    )
    return degraded_dp_extent(
        current_dp,
        global_batch=global_batch,
        task_chunk=int(cfg_dict.get("task_chunk", 0) or 0),
    )


def main() -> int:
    argv = sys.argv[1:]
    cfg = argv[0]
    extra = list(argv[1:])
    pause_every = _pop_flag(extra, "--pause_every", None, int)
    if pause_every is not None and pause_every < 1:
        raise SystemExit(f"--pause_every must be >= 1, got {pause_every}")
    # Requeue exits (rc 75) are preemption-safe: an emergency checkpoint
    # was written mid-epoch, so re-entering is always progress even though
    # no epoch row landed. They get their own (generous) budget instead of
    # consuming the phase budget — a heavily-preempted long run must not
    # abort as "budget exhausted" while advancing monotonically. Hang
    # exits (rc 76) get a SEPARATE budget for the same reason in reverse:
    # the two failure classes must not starve each other's recovery.
    max_requeues = _pop_flag(extra, "--max_requeues", 100, int)
    max_hangs = _pop_flag(extra, "--max_hangs", 8, int)

    entry = os.environ.get(ENTRY_ENV) or (
        "train_gradient_descent_system.py" if "gradient-descent" in cfg
        else "train_matching_nets_system.py" if "matching-nets" in cfg
        else "train_maml_system.py")
    # Canonical configs live in experiment_config/ (the reference's 38-file
    # surface, content-tested); local variants (bf16, resnet12, ...) in
    # experiment_config_local/ so regeneration identity stays intact.
    for d in ("experiment_config", "experiment_config_local"):
        cfg_path = f"{d}/{cfg}.json"
        if os.path.exists(cfg_path):
            break
    else:
        raise FileNotFoundError(f"no config named {cfg} in experiment_config"
                                "{,_local}/")
    with open(cfg_path) as f:
        cfg_dict = json.load(f)
    exp_name = cfg_dict["experiment_name"]
    total_epochs = int(cfg_dict.get("total_epochs", 100))
    summary_csv = os.path.join(exp_name, "logs", "summary_statistics.csv")
    test_csv = os.path.join(exp_name, "logs", "test_summary.csv")

    def epochs_logged() -> int:
        try:
            with open(summary_csv) as f:
                return max(sum(1 for _ in f) - 1, 0)
        except OSError:
            return 0

    if os.path.exists(test_csv):
        # Idempotent resume of a finished run: nothing to do. Explicit, so
        # a stale test_summary.csv can't silently mask an intended re-run —
        # delete the experiment dir (or its test_summary.csv) to redo.
        print(f"--- {cfg}: test eval already present at {test_csv}; "
              "nothing to run", flush=True)
        return 0

    # Config-key overrides are written into a patched config file rather
    # than passed as flags: the JSON wins over every flag except
    # continue_from/gpu_to_use (reference semantics, utils/parser_utils.py),
    # so a flag could be silently overridden by the config. experiment_name
    # is unchanged so logs, checkpoints and resume behave identically.
    overrides: dict = {}
    if pause_every is not None:
        overrides["total_epochs_before_pause"] = pause_every
    patched_path = None
    run_cfg_path = cfg_path

    def write_patched():
        nonlocal patched_path, run_cfg_path
        import tempfile

        if patched_path is not None:
            try:
                os.unlink(patched_path)
            except OSError:
                pass
            patched_path = None
        if not overrides:
            run_cfg_path = cfg_path
            return
        patched = tempfile.NamedTemporaryFile(
            "w", suffix=f"_{cfg}.json", delete=False
        )
        json.dump({**cfg_dict, **overrides}, patched)
        patched.close()
        run_cfg_path = patched_path = patched.name

    write_patched()

    # Degraded-mesh state: dp extents we stepped down from, newest last —
    # popped one level at each re-promotion probe.
    promote_stack: list[int] = []

    try:
        max_phases = 2 * (total_epochs // (pause_every or total_epochs) + 2)
        stalled = phase = requeues = hangs = signal_deaths = 0
        child_env = dict(os.environ)
        rc = 0
        while (
            phase < max_phases
            and requeues < max_requeues
            and hangs < max_hangs
        ):
            before = epochs_logged()
            print(f"--- {cfg}: phase {phase} via {entry} "
                  f"(epochs logged: {before}/{total_epochs})", flush=True)
            proc = subprocess.run(
                [sys.executable, "-u", entry, "--name_of_args_json_file",
                 run_cfg_path, *extra], check=False, env=child_env,
            )
            rc = proc.returncode
            # Env fault plans are one-shot per dispatcher run: the phase
            # that just ran consumed them; a requeued/degraded phase must
            # replay clean, not re-hit the same injected fault forever.
            child_env.pop("MAML_FAULTS", None)
            if os.path.exists(test_csv):
                break
            if rc == REQUEUE_EXIT_CODE:
                stalled = signal_deaths = 0
                requeues += 1
                continue
            died_by_signal = rc < 0 or rc > 128
            signal_deaths = signal_deaths + 1 if died_by_signal else 0
            if rc == HANG_EXIT_CODE or signal_deaths >= 2:
                # Suspect the topology: a wedged dispatch (watchdog
                # diagnostic in logs/hang_stacks.txt) or a device that
                # keeps killing its worker. Resume the same experiment on
                # the next-smaller viable mesh, from the last valid
                # checkpoint (mesh-portable restore).
                hangs += 1
                stalled = signal_deaths = 0
                current_dp = _resolved_dp(
                    {**cfg_dict, **overrides}, extra
                )
                smaller = _next_smaller_dp(cfg_dict, current_dp)
                why = ("hang" if rc == HANG_EXIT_CODE
                       else "repeated-signal-death")
                if smaller is not None:
                    promote_stack.append(current_dp)
                    overrides["data_parallel_devices"] = smaller
                    write_patched()
                    _audit_row(
                        exp_name,
                        f"{why}-degrade:dp{current_dp}->dp{smaller}",
                    )
                    print(f"--- {cfg}: {why} (rc {rc}); degrading mesh "
                          f"dp{current_dp} -> dp{smaller} and resuming "
                          "from the last valid checkpoint", flush=True)
                else:
                    _audit_row(exp_name, f"{why}-requeue:dp{current_dp}")
                    print(f"--- {cfg}: {why} (rc {rc}) with no smaller "
                          "viable mesh; requeueing on the same topology",
                          flush=True)
                continue
            phase += 1
            if epochs_logged() <= before:
                stalled += 1
                if stalled >= 2:
                    print(f"--- {cfg}: no progress across two phases, "
                          "aborting", flush=True)
                    return rc or 1
            else:
                stalled = 0
                if promote_stack:
                    # Re-promotion probe: the degraded mesh just completed
                    # a phase with real progress — try one step back up;
                    # a re-hang degrades again, on budget.
                    restored = promote_stack.pop()
                    overrides["data_parallel_devices"] = restored
                    write_patched()
                    _audit_row(exp_name, f"probe-promote:dp{restored}")
                    print(f"--- {cfg}: clean degraded phase; probing "
                          f"re-promotion to dp{restored}", flush=True)
        if hangs >= max_hangs:
            print(f"--- {cfg}: hang budget ({max_hangs}) exhausted, "
                  "aborting", flush=True)
            return rc or 1
        if not os.path.exists(test_csv):
            print(f"--- {cfg}: phase budget exhausted without test eval",
                  flush=True)
            return rc or 1
        print(f"--- {cfg}: done ({epochs_logged()} epochs + test eval, "
              f"final phase rc {rc})", flush=True)
        # Exit-code fidelity: the phase that produced the test eval still
        # decides the exit code (a teardown failure must not be masked).
        return rc
    finally:
        if patched_path is not None:
            try:
                os.unlink(patched_path)
            except OSError:
                pass


if __name__ == "__main__":
    sys.exit(main())
